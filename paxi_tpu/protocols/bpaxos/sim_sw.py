"""FROZEN pre-rewrite reference: the sliding-window (ring-position)
lane-major bpaxos kernel, kept verbatim from before the fixed-cell
rewrite (PR 15) as the equivalence-proof counterpart.

Ring layout contract (the OLD one): ring position ``i`` holds absolute
slot ``base + i``; every base advance is a ``ring.shift_window`` data
movement.  The live kernel in ``sim.py`` holds absolute slot ``a`` at
cell ``a % S`` forever (sim/cell.py) and must stay BIT-CANONICALLY
equal to this module on pinned fuzz seeds: same PRNG draws, same
outboxes, same counters, and a state that matches after rolling each
ring plane to window order (cell.window_view_np) —
tests/test_fixed_cell_equiv.py enforces it, and ``python -m paxi_tpu
profile --gathers`` diffs the two compiled HLOs' gather counts.  Do
not edit except to mirror a semantic (non-layout) change in sim.py.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim import inscan
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1    # empty log entry
NOOP = -2      # hole filled by takeover recovery

# grid-quorum thresholds: ONE complete row commits a write, ONE
# complete column completes a recovery read (paxi-lint PXQ rowcol
# sites — see _row_quorums/_col_quorums)
W_ROWS = 1
R_COLS = 1


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("bal", "slot"),
        "p1b": ("bal", "slot", "vbal", "vcmd", "vbsz"),
        "p2a": ("bal", "slot", "cmd", "bsz"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "bsz"),
    }


def encode_cmd(bal, slot):
    """Unique-ish batch id per (ballot, slot) — divergent decisions are
    visible to the agreement oracle.  Doubles as the KV write payload."""
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def _geometry(cfg: SimConfig):
    """(proxies, rows, cols, acceptors, executors) — static role split
    over the node axis."""
    P, GR, GC = cfg.n_proxies, cfg.grid_rows, cfg.grid_cols
    A = GR * GC
    E = cfg.n_replicas - P - A
    if P < 1 or GR < 1 or GC < 1 or E < 1:
        raise ValueError(
            f"bpaxos needs n_replicas >= n_proxies + grid_rows*grid_cols"
            f" + 1 (got R={cfg.n_replicas}, P={P}, grid={GR}x{GC})")
    return P, GR, GC, A, E


def _row_quorums(acks, cfg: SimConfig):
    """acks: (...) int32 bit-packed over nodes -> (...) count of grid
    rows FULLY acked (the BPaxos write-quorum primitive).  Acceptor
    (r, c) is node ``n_proxies + r*grid_cols + c``."""
    P, GR, GC = cfg.n_proxies, cfg.grid_rows, cfg.grid_cols
    cnt = jnp.zeros(acks.shape, jnp.int32)
    for r in range(GR):
        rmask = jnp.int32(((1 << GC) - 1) << (P + r * GC))
        per = jax.lax.population_count(acks & rmask)
        cnt = cnt + (per >= GC)
    return cnt


def _col_quorums(acks, cfg: SimConfig):
    """acks -> count of grid columns FULLY acked (the BPaxos
    read/recovery-quorum primitive)."""
    P, GR, GC = cfg.n_proxies, cfg.grid_rows, cfg.grid_cols
    cnt = jnp.zeros(acks.shape, jnp.int32)
    for c in range(GC):
        cmask = 0
        for r in range(GR):
            cmask |= 1 << (P + r * GC + c)
        per = jax.lax.population_count(acks & jnp.int32(cmask))
        cnt = cnt + (per >= GR)
    return cnt


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    P, GR, GC, A, E = _geometry(cfg)
    del rng, GR, GC, A, E
    require_packable(R)
    i32 = jnp.int32
    ridx = jnp.arange(R, dtype=i32)
    return dict(
        # acceptor rings (role-masked: meaningful at the grid nodes)
        abal=jnp.zeros((R, S, G), i32),       # promised ballot per slot
        vbal=jnp.zeros((R, S, G), i32),       # accepted ballot
        vcmd=jnp.full((R, S, G), NO_CMD, i32),  # accepted batch id
        vbsz=jnp.zeros((R, S, G), i32),       # accepted batch size
        committed=jnp.zeros((R, S, G), bool),  # learner commit bit
        # proxy bookkeeping (own stripe only)
        proposed=jnp.zeros((R, S, G), bool),
        p2_acks=jnp.zeros((R, S, G), i32),    # bit-packed over nodes
        next_slot=jnp.broadcast_to(ridx[:, None], (R, G)).astype(i32),
        # shared frontier: contiguous committed prefix, executed in
        # order at every non-acceptor (executors are the reply role)
        base=jnp.zeros((R, G), i32),
        execute=jnp.zeros((R, G), i32),
        kv=jnp.zeros((R, K, G), i32),
        cum_cmds=jnp.zeros((R, G), i32),      # commands executed (batch sum)
        stuck=jnp.zeros((R, G), i32),         # frontier-stall counter
        # per-proxy takeover-recovery FSM (one slot in flight at a time)
        rec_slot=jnp.full((R, G), -1, i32),
        rec_bal=jnp.zeros((R, G), i32),
        rec_phase=jnp.zeros((R, G), i32),     # 0 idle, 1 read, 2 write
        rec_acks=jnp.zeros((R, G), i32),
        rec_vbal=jnp.zeros((R, G), i32),
        rec_vcmd=jnp.full((R, G), NO_CMD, i32),
        rec_vbsz=jnp.zeros((R, G), i32),
        rec_round=jnp.zeros((R, G), i32),     # attempts (ballot rounds)
        rec_timer=jnp.zeros((R, G), i32),
        recovered=jnp.zeros((R, G), i32),     # completed takeovers (metric)
        # ---- on-device observability (``m_`` planes: excluded from
        # the witness hash, never read by protocol logic — PXM10x):
        # per-slot first-propose step at its proxy, the shared log2
        # commit-latency histogram (metrics/lathist) and the in-scan
        # linearizability spot-check accumulator (sim/inscan)
        m_prop_t=jnp.zeros((R, S, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )


def _step(state, inbox, ctx: StepCtx, *, read_quorum: bool = True):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    P, GR, GC, A, E = _geometry(cfg)
    STRIDE = cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    i32 = jnp.int32
    ridx = jnp.arange(R, dtype=i32)
    sidx = jnp.arange(S, dtype=i32)
    kidx = jnp.arange(K, dtype=i32)
    G = state["execute"].shape[-1]

    is_proxy = (ridx < P)[:, None]                       # (R, 1)
    is_acc = ((ridx >= P) & (ridx < P + A))[:, None]
    acc_row = jnp.where(ridx >= P, (ridx - P) // GC, -1)  # (R,)
    acc_col = jnp.where(ridx >= P, (ridx - P) % GC, -1)
    bal0 = (STRIDE + ridx)[:, None].astype(i32)          # proxy base ballot

    st = dict(state)
    abal, vbal = st["abal"], st["vbal"]
    vcmd, vbsz = st["vcmd"], st["vbsz"]
    committed = st["committed"]
    base, execute = st["base"], st["execute"]

    def at_slot(plane, oh):
        """Value of an (R, S, G) ring plane at a per-(R, G) one-hot."""
        return jnp.sum(jnp.where(oh, plane, 0), axis=1)

    def slot_oh(slot):
        rel = slot - base
        inw = (rel >= 0) & (rel < S)
        return sidx[None, :, None] == rel[:, None, :], inw

    def out_planes(fields):
        z = jnp.zeros((R, R, G), i32)
        out = {"valid": jnp.zeros((R, R, G), bool)}
        out.update({f: z for f in fields})
        return out

    def reply_to(out, dst, src_mask, **fields):
        """Emit a reply from every node where ``src_mask`` (src, G)
        holds to the single destination node ``dst``; field values are
        per-sender ``(src, G)`` planes."""
        dst_oh = (ridx == dst)[None, :, None]            # (1, R, 1)
        m = src_mask[:, None, :] & dst_oh
        out["valid"] = out["valid"] | m
        for k, v in fields.items():
            out[k] = jnp.where(m, v[:, None, :], out[k])
        return out

    # ------------- acceptors: P1a (column-read probes) ------------------
    out_p1b = out_planes(("bal", "slot", "vbal", "vcmd", "vbsz"))
    for s in range(P):
        m = inbox["p1a"]
        ok = m["valid"][s] & is_acc                      # (dst=R, G)
        bal, slot = m["bal"][s], m["slot"][s]
        oh, inw = slot_oh(slot)
        cur = at_slot(abal, oh)
        grant = ok & inw & (bal >= cur)
        abal = jnp.where(grant[:, None, :] & oh,
                         jnp.maximum(abal, bal[:, None, :]), abal)
        out_p1b = reply_to(
            out_p1b, s, grant, bal=bal, slot=slot,
            vbal=at_slot(vbal, oh), vcmd=at_slot(vcmd, oh),
            vbsz=at_slot(vbsz, oh))

    # ------------- acceptors: P2a (row-write accepts) -------------------
    out_p2b = out_planes(("bal", "slot"))
    for s in range(P):
        m = inbox["p2a"]
        ok = m["valid"][s] & is_acc
        bal, slot = m["bal"][s], m["slot"][s]
        cmd, bsz = m["cmd"][s], m["bsz"][s]
        oh, inw = slot_oh(slot)
        cur = at_slot(abal, oh)
        acc = ok & inw & (bal >= cur)
        w = acc[:, None, :] & oh
        abal = jnp.where(w, jnp.maximum(abal, bal[:, None, :]), abal)
        vbal = jnp.where(w, bal[:, None, :], vbal)
        vcmd = jnp.where(w, cmd[:, None, :], vcmd)
        vbsz = jnp.where(w, bsz[:, None, :], vbsz)
        out_p2b = reply_to(out_p2b, s, acc, bal=bal, slot=slot)

    # ------------- proxies: P1b (recovery-read tally) -------------------
    rec_slot, rec_bal = st["rec_slot"], st["rec_bal"]
    rec_phase, rec_acks = st["rec_phase"], st["rec_acks"]
    rec_vbal, rec_vcmd = st["rec_vbal"], st["rec_vcmd"]
    rec_vbsz = st["rec_vbsz"]
    for a in range(P, P + A):
        m = inbox["p1b"]
        ok = (m["valid"][a] & is_proxy & (rec_phase == 1)
              & (m["bal"][a] == rec_bal) & (m["slot"][a] == rec_slot))
        rec_acks = jnp.where(ok, rec_acks | i32(1 << a), rec_acks)
        better = ok & (m["vbal"][a] > rec_vbal)
        rec_vbal = jnp.where(better, m["vbal"][a], rec_vbal)
        rec_vcmd = jnp.where(better, m["vcmd"][a], rec_vcmd)
        rec_vbsz = jnp.where(better, m["vbsz"][a], rec_vbsz)

    # read quorum: ONE FULL COLUMN seen -> write the value (or NOOP)
    colq = _col_quorums(rec_acks, cfg)
    read_done = is_proxy & (rec_phase == 1) & (colq >= R_COLS)
    rec_vcmd = jnp.where(read_done & (rec_vbal <= 0), NOOP, rec_vcmd)
    rec_vbsz = jnp.where(read_done & (rec_vbal <= 0), 0, rec_vbsz)
    rec_phase = jnp.where(read_done, 2, rec_phase)
    rec_acks = jnp.where(read_done, 0, rec_acks)

    # ------------- proxies: P2b (normal + recovery tallies) -------------
    p2_acks = st["p2_acks"]
    for a in range(P, P + A):
        m = inbox["p2b"]
        ok = m["valid"][a] & is_proxy
        bal, slot = m["bal"][a], m["slot"][a]
        oh, inw = slot_oh(slot)
        norm = ok & (bal == bal0) & inw
        p2_acks = p2_acks | jnp.where(norm[:, None, :] & oh,
                                      i32(1 << a), 0)
        rec = (ok & (rec_phase == 2) & (bal == rec_bal)
               & (slot == rec_slot))
        rec_acks = jnp.where(rec, rec_acks | i32(1 << a), rec_acks)

    # write quorum: ONE FULL ROW of acks commits the slot
    rowq = _row_quorums(p2_acks, cfg)
    newly = (is_proxy[:, None, :] & st["proposed"] & ~committed
             & (rowq >= W_ROWS) & (vcmd != NO_CMD))
    committed = committed | newly
    # in-kernel commit-latency histogram: propose->commit step delta of
    # every newly committed (proxy, slot), log2-binned on device
    m_prop_t = st["m_prop_t"]
    lat_dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_lat_hist = lathist.hist_update(st["m_lat_hist"], lat_dt, newly)
    m_lat_sum = st["m_lat_sum"] + jnp.sum(jnp.where(newly, lat_dt, 0),
                                          axis=(0, 1), dtype=jnp.int32)

    rowq_rec = _row_quorums(rec_acks, cfg)
    rec_done = is_proxy & (rec_phase == 2) & (rowq_rec >= W_ROWS)
    oh_rec, rec_inw = slot_oh(rec_slot)
    w = (rec_done & rec_inw)[:, None, :] & oh_rec
    vcmd = jnp.where(w, rec_vcmd[:, None, :], vcmd)
    vbsz = jnp.where(w, rec_vbsz[:, None, :], vbsz)
    vbal = jnp.where(w, rec_bal[:, None, :], vbal)
    committed = committed | w
    recovered = st["recovered"] + rec_done
    rec_phase = jnp.where(rec_done, 0, rec_phase)
    rec_slot = jnp.where(rec_done, -1, rec_slot)

    # ------------- everyone: P3 (commit learn + laggard healing) --------
    kv, cum_cmds = st["kv"], st["cum_cmds"]
    proposed = st["proposed"]
    next_slot = st["next_slot"]
    for s in range(P):
        m = inbox["p3"]
        ok = m["valid"][s]
        bal, slot = m["bal"][s], m["slot"][s]
        cmd, bsz = m["cmd"][s], m["bsz"][s]
        # deep-laggard healing: my frontier fell below the sender's
        # window -> re-base my ring to the sender's window, keep my
        # entries (shifted, promises included) where the sender has no
        # commit, and adopt the sender's executed state wholesale.
        # Adoption is BY REFERENCE to the sender's live base/planes
        # (the wpaxos/ballot_ring precedent): a message-carried window
        # base goes stale between send and delivery as the sender's
        # ring slides, and re-basing to a stale base misaligns every
        # adopted slot.
        low = base[s][None, :]
        adopt = ok & (execute < low)
        a2 = adopt[:, None, :]
        adv_a = jnp.where(adopt, low - base, 0)
        my_abal = _shift(abal, adv_a, 0)
        my_vbal = _shift(vbal, adv_a, 0)
        my_vcmd = _shift(vcmd, adv_a, NO_CMD)
        my_vbsz = _shift(vbsz, adv_a, 0)
        my_com = _shift(committed, adv_a, False)
        s_com = committed[s][None]
        abal = jnp.where(a2, jnp.maximum(abal[s][None], my_abal), abal)
        vbal = jnp.where(a2, jnp.where(s_com, vbal[s][None], my_vbal),
                         vbal)
        vcmd = jnp.where(a2, jnp.where(s_com, vcmd[s][None], my_vcmd),
                         vcmd)
        vbsz = jnp.where(a2, jnp.where(s_com, vbsz[s][None], my_vbsz),
                         vbsz)
        committed = jnp.where(a2, s_com | my_com, committed)
        proposed = jnp.where(a2, False, proposed)
        p2_acks = jnp.where(a2, 0, p2_acks)
        m_prop_t = jnp.where(a2, 0, m_prop_t)  # adopted rows: new clocks
        kv = jnp.where(adopt[:, None, :], kv[s][None], kv)
        cum_cmds = jnp.where(adopt, cum_cmds[s][None], cum_cmds)
        execute = jnp.where(adopt, execute[s][None, :], execute)
        base = jnp.where(adopt, low, base)
        # keep proxy stripes aligned after a frontier jump
        nxt = execute + ((ridx[:, None] - execute) % P)
        next_slot = jnp.where(adopt & is_proxy,
                              jnp.maximum(next_slot, nxt), next_slot)
        # the message's own slot: commit exactly what it says (the
        # promise rises with it, so a learned commit never reads as an
        # accept without a promise)
        oh, inw = slot_oh(slot)
        w = (ok & inw)[:, None, :] & oh
        vcmd = jnp.where(w, cmd[:, None, :], vcmd)
        vbsz = jnp.where(w, bsz[:, None, :], vbsz)
        vbal = jnp.where(w, jnp.maximum(vbal, bal[:, None, :]), vbal)
        abal = jnp.where(w, jnp.maximum(abal, bal[:, None, :]), abal)
        committed = committed | w

    # ------------- recovery abort: the slot got committed ---------------
    oh_rec, rec_inw = slot_oh(rec_slot)
    rec_com = jnp.any(oh_rec & committed, axis=1)
    drop_rec = (rec_phase > 0) & (rec_com | (rec_slot < base))
    rec_phase = jnp.where(drop_rec, 0, rec_phase)
    rec_slot = jnp.where(drop_rec, -1, rec_slot)

    # ------------- execute the contiguous committed prefix --------------
    abs_ = base[:, None, :] + sidx[None, :, None]
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(execute, dtype=bool)
    for e in range(cfg.exec_window):
        rel = execute + e - base
        oh_e = sidx[None, :, None] == rel[:, None, :]
        com = jnp.any(oh_e & committed, axis=1)
        running = running & com
        cmd_e = at_slot(vcmd, oh_e)
        bsz_e = at_slot(vbsz, oh_e)
        wr = running & (cmd_e >= 0)
        key_e = fib_key(cmd_e, K)
        ohk = wr[:, None, :] & (kidx[None, :, None] == key_e[:, None, :])
        kv = jnp.where(ohk, cmd_e[:, None, :], kv)
        cum_cmds = cum_cmds + jnp.where(wr, bsz_e, 0)
        advanced = advanced + running
    new_execute = execute + advanced

    # ------------- proxies: propose (fresh batch or re-proposal) --------
    stuck = jnp.where(is_proxy & (advanced == 0), st["stuck"] + 1, 0)
    own = (abs_ % P) == ridx[:, None, None]
    # go-back-N reopen: a dropped P2a/P2b leaves its slot unproposable;
    # on a stall re-open every own in-flight slot (drains in O(N)
    # steps).  The counter keeps growing while stalled — it also arms
    # the takeover trigger below, so it must not reset on retry.
    retry = (stuck > 0) & (stuck % cfg.retry_timeout == 0)
    reopen = (retry[:, None, :] & own & proposed & ~committed
              & (abs_ < next_slot[:, None, :]))
    proposed = proposed & ~reopen

    mask_re = (is_proxy[:, None, :] & own & ~proposed & ~committed
               & (abs_ < next_slot[:, None, :]))
    first_re = jnp.argmin(jnp.where(mask_re, sidx[None, :, None], S),
                          axis=1).astype(i32)
    has_re = jnp.any(mask_re, axis=1)
    can_new = (next_slot - base) < S
    rel_new = jnp.clip(next_slot - base, 0, S - 1)
    prop_rel = jnp.where(has_re, first_re, rel_new)
    prop_slot = base + prop_rel
    oh_p = sidx[None, :, None] == prop_rel[:, None, :]
    # skip own fresh slots someone else already recovered (NOOP-filled)
    fresh_com = jnp.any(oh_p & committed, axis=1)
    is_new = ~has_re & can_new
    skip = is_proxy & is_new & fresh_com
    next_slot = next_slot + jnp.where(skip, P, 0)
    # the HT-Paxos batch: one grid round will commit bsz commands
    draw = jr.randint(jr.fold_in(ctx.rng, 23), (R, G), 1,
                      cfg.batch_max + 1)
    new_cmd = encode_cmd(bal0, prop_slot)
    prop_cmd = jnp.where(is_new, new_cmd, at_slot(vcmd, oh_p))
    prop_cmd = jnp.where(prop_cmd == NO_CMD, NOOP, prop_cmd)
    prop_bsz = jnp.where(is_new, draw, at_slot(vbsz, oh_p))
    do = (is_proxy & (has_re | is_new) & ~skip & ~(rec_phase == 2)
          & ~(is_new & fresh_com))
    ohw = do[:, None, :] & oh_p & ~committed
    vcmd = jnp.where(ohw, prop_cmd[:, None, :], vcmd)
    vbsz = jnp.where(ohw, prop_bsz[:, None, :], vbsz)
    vbal = jnp.where(ohw, bal0[:, None, :], vbal)
    # latency clock: a slot's FIRST propose starts it (go-back-N
    # reopens keep the original start; recycled cells re-arm via the
    # slide's 0 fill)
    m_prop_t = jnp.where(do[:, None, :] & oh_p & ~proposed
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    proposed = proposed | (do[:, None, :] & oh_p)
    next_slot = next_slot + jnp.where(is_new & do, P, 0)

    # ------------- outgoing P2a: thrifty row-targeted -------------------
    do_recw = is_proxy & (rec_phase == 2)
    p2a_bal = jnp.where(do_recw, rec_bal, bal0)
    p2a_slot = jnp.where(do_recw, rec_slot, prop_slot)
    p2a_cmd = jnp.where(do_recw, rec_vcmd, prop_cmd)
    p2a_bsz = jnp.where(do_recw, rec_vbsz, prop_bsz)
    row_t = jnp.where(do_recw, st["rec_round"] % GR, p2a_slot % GR)
    p2a_do = do | do_recw
    row_hit = (acc_row[None, :, None] == row_t[:, None, :]) \
        & is_acc[None, :, :]
    out_p2a = {
        "valid": p2a_do[:, None, :] & row_hit,
        "bal": jnp.broadcast_to(p2a_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(p2a_slot[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(p2a_cmd[:, None, :], (R, R, G)),
        "bsz": jnp.broadcast_to(p2a_bsz[:, None, :], (R, R, G)),
    }

    # ------------- outgoing P1a: thrifty column-targeted ----------------
    do_read = is_proxy & (rec_phase == 1)
    col_t = st["rec_round"] % GC
    col_hit = (acc_col[None, :, None] == col_t[:, None, :]) \
        & is_acc[None, :, :]
    out_p1a = {
        "valid": do_read[:, None, :] & col_hit,
        "bal": jnp.broadcast_to(rec_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(rec_slot[:, None, :], (R, R, G)),
    }

    # ------------- outgoing P3: fresh commit else retransmit ------------
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :, None], S),
                         axis=1).astype(i32)
    any_new = jnp.any(newly, axis=1)
    span = jnp.maximum(new_execute - base, 1)
    p3_rel = jnp.where(any_new, low_new, ctx.t % span)
    p3_rel = jnp.where(rec_done & rec_inw,
                       jnp.clip(rec_slot - base, 0, S - 1), p3_rel)
    p3_rel = jnp.clip(p3_rel, 0, S - 1).astype(i32)
    oh_3 = sidx[None, :, None] == p3_rel[:, None, :]
    p3_commit = jnp.any(oh_3 & committed, axis=1)
    p3_do = is_proxy & p3_commit
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(at_slot(vbal, oh_3)[:, None, :],
                                (R, R, G)),
        "slot": jnp.broadcast_to((base + p3_rel)[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(at_slot(vcmd, oh_3)[:, None, :],
                                (R, R, G)),
        "bsz": jnp.broadcast_to(at_slot(vbsz, oh_3)[:, None, :],
                                (R, R, G)),
    }

    # ------------- takeover trigger + recovery restart ------------------
    hole_oh = sidx[None, :, None] == (new_execute - base)[:, None, :]
    hole_com = jnp.any(hole_oh & committed, axis=1)
    evid = jnp.any(committed & (abs_ > new_execute[:, None, :]), axis=1)
    owner = new_execute % P
    stag = (ridx[:, None] - owner) % P
    fire = (is_proxy & (rec_phase == 0) & evid & ~hole_com
            & (stuck >= cfg.election_timeout + 3 * stag))
    rec_round = st["rec_round"]
    # in-flight recovery stalls (dropped probes, dead row/column
    # members): bump the ballot round and rotate row + column
    restart = (rec_phase > 0) & (st["rec_timer"] >= cfg.election_timeout)
    rec_timer = jnp.where((rec_phase > 0) & ~restart,
                          st["rec_timer"] + 1, 0)
    go = fire | restart
    rec_round = jnp.where(go, rec_round + 1, rec_round)
    rec_slot = jnp.where(fire, new_execute, rec_slot)
    rec_bal = jnp.where(go, STRIDE * (1 + rec_round) + ridx[:, None],
                        rec_bal)
    # the seeded-bug twin (read_quorum=False) jumps straight to the
    # row write with NOOP — skipping exactly the column read whose
    # intersection with every write row makes takeover safe
    rec_phase = jnp.where(go, 1 if read_quorum else 2, rec_phase)
    rec_acks = jnp.where(go, 0, rec_acks)
    rec_vbal = jnp.where(go, 0, rec_vbal)
    rec_vcmd = jnp.where(go, NO_CMD if read_quorum else NOOP, rec_vcmd)
    rec_vbsz = jnp.where(go, 0, rec_vbsz)

    # a committed value's ballot is done: the promise rises with every
    # commit path (tally/recovery/p3), keeping accepted <= promised
    abal = jnp.maximum(abal, jnp.where(committed, vbal, 0))

    # ------------- slide the ring past the executed prefix --------------
    new_base = jnp.maximum(base, new_execute - RETAIN)
    adv = new_base - base
    new_committed = _shift(committed, adv, False)
    new_vcmd = _shift(vcmd, adv, NO_CMD)

    # in-scan linearizability spot-check (sim/inscan): an independent
    # oracle beside invariants(), accumulated on device per group
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], new_execute, state["base"], new_base,
        state["base"][:, None, :] + sidx[None, :, None],
        new_base[:, None, :] + sidx[None, :, None],
        state["vcmd"], new_vcmd,
        state["committed"], new_committed,
        kv=kv, lane_major=True)

    new_state = dict(
        abal=_shift(abal, adv, 0), vbal=_shift(vbal, adv, 0),
        vcmd=new_vcmd, vbsz=_shift(vbsz, adv, 0),
        committed=new_committed,
        proposed=_shift(proposed, adv, False),
        p2_acks=_shift(p2_acks, adv, 0),
        next_slot=next_slot, base=new_base, execute=new_execute,
        kv=kv, cum_cmds=cum_cmds, stuck=stuck,
        rec_slot=rec_slot, rec_bal=rec_bal, rec_phase=rec_phase,
        rec_acks=rec_acks, rec_vbal=rec_vbal, rec_vcmd=rec_vcmd,
        rec_vbsz=rec_vbsz, rec_round=rec_round, rec_timer=rec_timer,
        recovered=recovered,
        m_prop_t=_shift(m_prop_t, adv, 0), m_lat_hist=m_lat_hist,
        m_lat_sum=m_lat_sum, m_inscan_viol=m_inscan_viol,
    )
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots = the most advanced frontier; committed_cmds
    counts the commands inside those slots (the HT-Paxos amortization
    is committed_cmds / committed_slots); summed over the group axis."""
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "committed_cmds": jnp.sum(jnp.max(state["cum_cmds"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=0)),
        "recoveries": jnp.sum(state["recovered"]),
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": jnp.sum(state["m_lat_hist"]),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Per-step safety oracle:
    1. Agreement: all committed (batch id, batch size) for a slot are
       equal across nodes (base-aligned common window).
    2. Stability: a committed entry never changes value/size or
       un-commits while in-window; recycled slots were executed.
    3. Promise monotonicity: ``abal`` never decreases per slot, and
       accepted ballots never exceed the promise.
    4. Executed prefix is committed (within the window).
    5. Batch sanity: committed batch sizes are in 0..batch_max."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c = new["base"], new["committed"]
    cmd, bsz = new["vcmd"], new["vbsz"]

    # 1. agreement on the aligned window
    align = jnp.max(base, axis=0)[None, :] - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    a_bsz = _shift(bsz, align, 0)
    n_c = jnp.sum(a_c, axis=0)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    bx = jnp.max(jnp.where(a_c, a_bsz, -BIG), axis=0)
    bn = jnp.min(jnp.where(a_c, a_bsz, BIG), axis=0)
    v_agree = jnp.sum((n_c >= 1) & ((mx != mn) | (bx != bn)))

    # 2. stability
    adv = base - old["base"]
    o_c = _shift(old["committed"], adv, False)
    o_cmd = _shift(old["vcmd"], adv, NO_CMD)
    o_bsz = _shift(old["vbsz"], adv, 0)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd) | (bsz != o_bsz)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    # 3. promise monotonicity + accepted <= promised
    o_abal = _shift(old["abal"], adv, 0)
    v_bal = jnp.sum(new["abal"] < o_abal)
    P, GR, GC, A, E = _geometry(cfg)
    ridx = jnp.arange(cfg.n_replicas, dtype=jnp.int32)
    is_acc = ((ridx >= P) & (ridx < P + A))[:, None, None]
    v_bal = v_bal + jnp.sum(is_acc & (new["vbal"] > new["abal"]))

    # 4. executed prefix committed
    abs_ = base[:, None, :] + sidx[None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, None, :]) & ~c)

    # 5. batch sizes sane
    v_bsz = jnp.sum(c & ((bsz < 0) | (bsz > cfg.batch_max)))

    return (v_agree + v_stable + v_bal + v_exec + v_bsz).astype(jnp.int32)


def step(state, inbox, ctx: StepCtx):
    return _step(state, inbox, ctx, read_quorum=True)


PROTOCOL = SimProtocol(
    name="bpaxos_sw",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
