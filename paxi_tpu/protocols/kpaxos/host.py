"""KPaxos replica for the host (deployment) runtime.

Reference: paxi kpaxos/ — statically key-partitioned Paxos: partition =
``key % N`` and each partition is owned by a fixed leader (sorted config
order) running an independent per-partition Paxos log; requests landing
on a non-owner are forwarded (node.go Forward).  The static-ownership
contrast case to wpaxos's dynamic object stealing.

With ownership fixed there are no elections and no ballot races: the
owner runs phase-2 only (accept/commit), which is exactly the
steady-state Multi-Paxos path.  The same protocol runs as a vmapped TPU
kernel in ``sim.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node


@register_message
@dataclass
class KP2a:
    part: int
    slot: int
    key: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class KP2b:
    part: int
    slot: int
    id: str


@register_message
@dataclass
class KP3:
    part: int
    slot: int
    key: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@dataclass
class Entry:
    command: Command
    commit: bool = False
    request: Optional[Request] = None
    quorum: Optional[Quorum] = None


class Partition:
    """One static-leader Paxos log (kpaxos's per-partition paxos.Paxos)."""

    def __init__(self):
        self.log: Dict[int, Entry] = {}
        self.slot = -1
        self.execute = 0


class KPaxosReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.order = sorted(cfg.ids)
        self.parts: Dict[int, Partition] = {
            p: Partition() for p in range(len(self.order))}
        self.register(Request, self.handle_request)
        self.register(KP2a, self.handle_p2a)
        self.register(KP2b, self.handle_p2b)
        self.register(KP3, self.handle_p3)

    def partition_of(self, key: int) -> int:
        return key % len(self.order)

    def owner(self, part: int) -> ID:
        return self.order[part]

    # ---- client requests ----------------------------------------------
    def handle_request(self, req: Request) -> None:
        part = self.partition_of(req.command.key)
        owner = self.owner(part)
        if owner != self.id:
            self.forward(owner, req)
            return
        pt = self.parts[part]
        pt.slot += 1
        slot = pt.slot
        q = Quorum(self.cfg.ids)
        q.ack(self.id)
        c = req.command
        pt.log[slot] = Entry(c, request=req, quorum=q)
        self.socket.broadcast(KP2a(part, slot, c.key, c.value,
                                   c.client_id, c.command_id))
        if q.majority():  # single-replica cluster
            self._commit(part, slot)

    # ---- phase 2 -------------------------------------------------------
    def handle_p2a(self, m: KP2a) -> None:
        pt = self.parts[m.part]
        e = pt.log.get(m.slot)
        if e is None or not e.commit:
            req = e.request if e else None
            pt.log[m.slot] = Entry(Command(m.key, m.value, m.client_id,
                                           m.command_id), request=req)
        pt.slot = max(pt.slot, m.slot)
        self.socket.send(self.owner(m.part),
                         KP2b(m.part, m.slot, str(self.id)))

    def handle_p2b(self, m: KP2b) -> None:
        e = self.parts[m.part].log.get(m.slot)
        if e is not None and not e.commit and e.quorum is not None:
            e.quorum.ack(ID(m.id))
            if e.quorum.majority():
                self._commit(m.part, m.slot)

    def _commit(self, part: int, slot: int) -> None:
        e = self.parts[part].log[slot]
        e.commit = True
        c = e.command
        self.socket.broadcast(KP3(part, slot, c.key, c.value,
                                  c.client_id, c.command_id))
        self._exec(part)

    def handle_p3(self, m: KP3) -> None:
        pt = self.parts[m.part]
        e = pt.log.get(m.slot)
        req = e.request if e else None
        pt.log[m.slot] = Entry(Command(m.key, m.value, m.client_id,
                                       m.command_id), commit=True,
                               request=req)
        pt.slot = max(pt.slot, m.slot)
        self._exec(m.part)

    def _exec(self, part: int) -> None:
        pt = self.parts[part]
        while True:
            e = pt.log.get(pt.execute)
            if e is None or not e.commit:
                break
            value = self.db.execute(e.command)
            if e.request is not None:
                e.request.reply(Reply(e.command, value=value))
                e.request = None
            pt.execute += 1


def new_replica(id: ID, cfg: Config) -> KPaxosReplica:
    return KPaxosReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  Wire-level identity (cf. paxos/host.py):
# the partitioned phase-2 planes are the host's three message classes.
TRACE_MSG_MAP = {
    "p2a": "KP2a", "p2b": "KP2b", "p3": "KP3",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    "log_cmd":    "log",     # per-partition ring <-> _Part.log entries
    "log_commit": "log",
    "acks":       "quorum",  # leader ack bitmask <-> Entry.quorum
    "next_slot":  "slot",
    "kv":         "db",
    "base":       "",  # ring-window base: host logs are unbounded dicts
    "stuck":      "",  # frontier-stall retry counter (kernel-only)
}
