"""KPaxos — statically key-partitioned Multi-Paxos as a pure TPU kernel.

Reference: paxi kpaxos/ — the key space is split into static partitions,
each owned by a fixed leader running its own Paxos log (per-partition
``paxos.Paxos`` instances); the contrast case to WPaxos's dynamic object
stealing.  With leaders fixed there are no elections: every replica
permanently runs phase-2 for its own partition and accepts for all
others.

TPU re-design — the multi-leader structure is a *vectorization win*:
partition index == leader index, so a replica's inbox holds up to R
concurrent P2a messages (one per partition/source) and all of them are
applied in one masked scatter — no argmax winner-pick like the
single-leader paxos kernel needs.  Per-replica state carries an
(R partitions x S slots) log replica-of-record; commit = majority
popcount over the leader's per-slot ack matrix; execution advances an
independent frontier per partition.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    # partition is implicit: == src for p2a/p3, == dst for p2b
    return {
        "p2a": ("slot", "cmd"),
        "p2b": ("slot",),
        "p3": ("slot", "cmd", "upto"),
    }


def encode_cmd(part, slot):
    """Unique command id per (partition, slot) proposal."""
    return ((part & 0x7FFF) << 16) | (slot & 0xFFFF)


def init_state(cfg: SimConfig, rng: jax.Array):
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    del rng
    return dict(
        # replica-of-record logs: [replica, partition, slot]
        log_cmd=jnp.full((R, R, S), NO_CMD, jnp.int32),
        log_commit=jnp.zeros((R, R, S), bool),
        # leader-side state for my own partition
        acks=jnp.zeros((R, S, R), bool),   # [ldr, slot, src]
        next_slot=jnp.zeros((R,), jnp.int32),
        # execution frontier per partition at each replica
        execute=jnp.zeros((R, R), jnp.int32),
        kv=jnp.zeros((R, K), jnp.int32),
        stuck=jnp.zeros((R,), jnp.int32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ = cfg.majority
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)

    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    acks = state["acks"]
    next_slot = state["next_slot"]
    execute = state["execute"]
    kv = state["kv"]

    # ---------------- P2a: accept for partition == src ------------------
    m = inbox["p2a"]
    # scatter (src, dst) messages into [dst(replica), src(partition), slot]
    v = jnp.transpose(m["valid"])                  # (dst, src)
    slot = jnp.transpose(m["slot"])
    cmd = jnp.transpose(m["cmd"])
    oh = v[:, :, None] & (sidx[None, None, :] == slot[:, :, None])
    wr = oh & ~log_commit                          # committed entries frozen
    log_cmd = jnp.where(wr, cmd[:, :, None], log_cmd)
    # reply to the leader: outbox planes are [sender, recipient]; the
    # sender is this acceptor (our dst axis), the recipient the p2a's src
    out_p2b = {"valid": v, "slot": slot}

    # ---------------- P2b: leader tallies, commits own partition --------
    m = inbox["p2b"]
    okb = jnp.transpose(m["valid"])                # (ldr, src)
    bslot = jnp.transpose(m["slot"])
    add = okb[:, :, None] & (sidx[None, None, :] == bslot[:, :, None])
    acks = acks | jnp.transpose(add, (0, 2, 1))    # (ldr, slot, src)
    mine = log_cmd[ridx, ridx]                     # (ldr, S) my partition log
    newly = ((jnp.sum(acks, axis=2) >= MAJ) & (mine != NO_CMD)
             & ~log_commit[ridx, ridx])
    self_part = ridx[:, None, None] == ridx[None, :, None]  # (rep,part,1)
    log_commit = log_commit | (self_part & newly[:, None, :])

    # ---------------- P3: commit notifications for partition == src -----
    m = inbox["p3"]
    v = jnp.transpose(m["valid"])                  # (dst, src)
    slot = jnp.transpose(m["slot"])
    cmd = jnp.transpose(m["cmd"])
    upto = jnp.transpose(m["upto"])
    oh = v[:, :, None] & (sidx[None, None, :] == slot[:, :, None])
    log_cmd = jnp.where(oh, cmd[:, :, None], log_cmd)
    log_commit = log_commit | oh
    # frontier rule: a static leader proposes exactly one command per
    # slot, so any locally-accepted slot < upto is safe to commit
    ohu = (v[:, :, None] & (sidx[None, None, :] < upto[:, :, None])
           & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- leader proposes in its own partition --------------
    # new slot while the pipe is healthy; retransmit the frontier slot
    # when it has stalled for retry_timeout steps (lost p2a/p2b)
    my_exec = execute[ridx, ridx]                  # (ldr,)
    retry = state["stuck"] >= cfg.retry_timeout
    can_new = next_slot < S
    prop_slot = jnp.where(retry, jnp.clip(my_exec, 0, S - 1),
                          next_slot).astype(jnp.int32)
    do = can_new | retry
    new_cmd = encode_cmd(ridx, prop_slot)
    re_cmd = mine[ridx, jnp.clip(prop_slot, 0, S - 1)]
    prop_cmd = jnp.where(retry & (re_cmd != NO_CMD), re_cmd, new_cmd)
    # self-accept + self-ack
    ohp = do[:, None] & (sidx[None, :] == prop_slot[:, None])
    self_row = self_part & ohp[:, None, :]
    log_cmd = jnp.where(self_row & ~log_commit, prop_cmd[:, None, None],
                        log_cmd)
    acks = acks | (ohp[:, :, None] & (ridx[None, None, :] == ridx[:, None, None]))
    next_slot = next_slot + (do & ~retry & can_new)
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None], (R, R)),
        "slot": jnp.broadcast_to(prop_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None], (R, R)),
    }

    # ---------------- execute committed prefixes, apply to KV -----------
    # each replica advances R independent frontiers; keys are partition-
    # striped (key = part + R * hash) so applies never conflict
    advanced = jnp.zeros((R, R), jnp.int32)
    running = jnp.ones((R, R), bool)
    for e in range(cfg.exec_window):
        idx = jnp.clip(execute + e, 0, S - 1)      # (rep, part)
        inb = (execute + e) < S
        com = jnp.take_along_axis(log_commit, idx[:, :, None], axis=2)[..., 0]
        running = running & com & inb
        cmd_e = jnp.take_along_axis(log_cmd, idx[:, :, None], axis=2)[..., 0]
        key_e = (ridx[None, :] + R * fib_key(cmd_e, max(K // R, 1))) % K
        wr = running & (cmd_e >= 0)
        ohk = wr[:, :, None] & (jnp.arange(K)[None, None, :] == key_e[:, :, None])
        kv = jnp.where(jnp.any(ohk, axis=1),
                       jnp.max(jnp.where(ohk, cmd_e[:, :, None], -1), axis=1),
                       kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- stuck-frontier counter (drives retransmits) -------
    my_exec_new = new_execute[ridx, ridx]
    stalled = (my_exec_new == my_exec) & (next_slot > my_exec_new)
    stuck = jnp.where(retry, 0, jnp.where(stalled, state["stuck"] + 1, 0))

    # ---------------- P3 out: newly committed or frontier retransmit ----
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :], S), axis=1)
    any_new = jnp.any(newly, axis=1)
    # otherwise cycle retransmits through my committed prefix (leader-
    # local knowledge only: laggards' holes are all < my frontier, so a
    # round-robin over it eventually re-covers every hole)
    rr = ctx.t % jnp.maximum(my_exec_new, 1)
    p3_slot = jnp.where(any_new, low_new,
                        jnp.clip(rr, 0, S - 1)).astype(jnp.int32)
    p3_committed = log_commit[ridx, ridx, p3_slot]
    p3_cmd = mine[ridx, p3_slot]
    p3_do = p3_committed
    my_upto = new_execute[ridx, ridx]
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None], (R, R)),
        "slot": jnp.broadcast_to(p3_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None], (R, R)),
        "upto": jnp.broadcast_to(my_upto[:, None], (R, R)),
    }

    new_state = dict(
        log_cmd=log_cmd, log_commit=log_commit, acks=acks,
        next_slot=next_slot, execute=new_execute, kv=kv, stuck=stuck,
    )
    outbox = {"p2a": out_p2a, "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots summed over all partitions (most advanced copy)."""
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.min(state["execute"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Agreement: committed commands for a (partition, slot) agree.
    2. Stability: committed entries never change or un-commit.
    3. Executed prefix is committed."""
    BIG = jnp.int32(2**30)
    c, cmd = new["log_commit"], new["log_cmd"]
    mx = jnp.max(jnp.where(c, cmd, -BIG), axis=0)   # (part, slot)
    mn = jnp.min(jnp.where(c, cmd, BIG), axis=0)
    n_c = jnp.sum(c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    was = old["log_commit"]
    v_stable = jnp.sum(was & (~c | (cmd != old["log_cmd"])))

    prefix_len = jnp.sum(jnp.cumprod(c.astype(jnp.int32), axis=2), axis=2)
    v_exec = jnp.sum(new["execute"] > prefix_len)

    return (v_agree + v_stable + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="kpaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
)
