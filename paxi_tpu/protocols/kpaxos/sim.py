"""KPaxos — statically key-partitioned Multi-Paxos as a pure TPU kernel.

Reference: paxi kpaxos/ — the key space is split into static partitions,
each owned by a fixed leader running its own Paxos log (per-partition
``paxos.Paxos`` instances); the contrast case to WPaxos's dynamic object
stealing.  With leaders fixed there are no elections: every replica
permanently runs phase-2 for its own partition and accepts for all
others.

TPU re-design — the multi-leader structure is a *vectorization win*:
partition index == leader index, so a replica's inbox holds up to R
concurrent P2a messages (one per partition/source) and all of them are
applied in one masked scatter — no argmax winner-pick like the
single-leader paxos kernel needs.

- **Lane-major batch layout** (see sim/lanes.py): state ``(R, G)`` /
  ``(R, P, S, G)``, mailbox planes ``(src, dst, G)``; ``Quorum.ACK``
  is a bit-packed int32 mask per (leader, slot) with
  ``lax.population_count`` for ``Majority()`` (quorum.go [driver]).
- Per-replica state carries an (R partitions x S slots) **ring** per
  partition: position i holds absolute slot base + i; each (replica,
  partition) window slides with its execute frontier, retaining the
  last S//2 executed slots (SURVEY §7 slot recycling — the horizon is
  unbounded).  Messages carry absolute slots; out-of-window slots are
  silently ignored and an acceptor acks only what it durably stored.
- P3 carries a commit frontier ``upto`` plus the leader's window base
  ``lowslot``: a replica whose frontier for that partition fell below
  ``lowslot`` adopts the leader's partition row (log, base, execute)
  and KV stripe by reference — snapshot catch-up for deep laggards,
  the state-transfer analog of the host runtime.
- Keys are partition-striped (key = part + R * hash, collision-free
  for n_keys >= n_replicas) so applies never conflict across
  partitions.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.ring import (diag2, dst_major, require_packable,
                               shift_window)
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    # partition is implicit: == src for p2a/p3, == dst for p2b
    return {
        "p2a": ("slot", "cmd"),
        "p2b": ("slot",),
        "p3": ("slot", "cmd", "upto", "lowslot"),
    }


def encode_cmd(part, slot):
    """Unique command id per (partition, slot) proposal."""
    return ((part & 0x7FFF) << 16) | (slot & 0xFFFF)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    require_packable(R)
    i32 = jnp.int32
    return dict(
        # replica-of-record ring logs: [replica, partition, slot, G]
        log_cmd=jnp.full((R, R, S, G), NO_CMD, i32),
        log_commit=jnp.zeros((R, R, S, G), bool),
        base=jnp.zeros((R, R, G), i32),     # abs slot of ring pos 0
        # leader-side ack bitmask for my own partition, base-aligned to
        # base[ldr, ldr]
        acks=jnp.zeros((R, S, G), i32),
        next_slot=jnp.zeros((R, G), i32),   # absolute
        # execution frontier per partition at each replica (absolute)
        execute=jnp.zeros((R, R, G), i32),
        kv=jnp.zeros((R, K, G), i32),
        stuck=jnp.zeros((R, G), i32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ = cfg.majority
    RETAIN = max(S // 2, 1)
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)

    log_cmd = state["log_cmd"]            # (R, P, S, G)
    log_commit = state["log_commit"]
    base = state["base"]                  # (R, P, G)
    acks = state["acks"]                  # (R, S, G) bitmask
    next_slot = state["next_slot"]
    execute = state["execute"]            # (R, P, G)
    kv = state["kv"]
    G = next_slot.shape[-1]

    T = dst_major  # mailbox (src, dst, G) -> (me=dst, src=partition, G)

    diag = diag2   # (R, P, ...) -> (R, ...) at part == replica

    # ---------------- P2a: accept for partition == src ------------------
    m = inbox["p2a"]
    v = T(m["valid"])                              # (me, part, G)
    slot = T(m["slot"])                            # absolute
    cmd = T(m["cmd"])
    rel = slot - base                              # (me, part, G) ring pos
    inw = (rel >= 0) & (rel < S)
    oh = (v & inw)[:, :, None, :] & (sidx[None, None, :, None]
                                     == rel[:, :, None, :])
    wr = oh & ~log_commit                          # committed entries frozen
    log_cmd = jnp.where(wr, cmd[:, :, None, :], log_cmd)
    # ack ONLY what we durably stored (a slot outside our window was
    # dropped; acking it would let the leader commit an entry no
    # majority holds).  Reply planes are [sender=me, recipient=part].
    out_p2b = {"valid": v & inw, "slot": slot}

    # ---------------- P2b: leader tallies, commits own partition --------
    m = inbox["p2b"]
    okb = T(m["valid"])                            # (ldr, src, G)
    bslot = T(m["slot"])
    base_own = diag(base)                          # (ldr, G)
    brel = bslot - base_own[:, None, :]            # (ldr, src, G)
    for s in range(R):
        oh_s = okb[:, s][:, None, :] & (sidx[None, :, None]
                                        == brel[:, s][:, None, :])
        acks = acks | jnp.where(oh_s, jnp.int32(1) << s, 0)
    mine = diag(log_cmd)                           # (ldr, S, G)
    mine_com = diag(log_commit)
    newly = ((jax.lax.population_count(acks) >= MAJ)
             & (mine != NO_CMD) & ~mine_com)
    part_oh = (ridx[:, None] == ridx[None, :])[:, :, None, None]  # (R,P,1,1)
    log_commit = log_commit | (part_oh & newly[:, None])

    # ---------------- P3: commit notifications for partition == src -----
    m = inbox["p3"]
    v = T(m["valid"])                              # (me, part, G)
    slot = T(m["slot"])
    cmd = T(m["cmd"])
    upto = T(m["upto"])
    lowslot = T(m["lowslot"])
    rel = slot - base
    inw = (rel >= 0) & (rel < S)
    oh = (v & inw)[:, :, None, :] & (sidx[None, None, :, None]
                                     == rel[:, :, None, :])
    log_cmd = jnp.where(oh, cmd[:, :, None, :], log_cmd)
    log_commit = log_commit | oh
    # frontier rule: a static leader proposes exactly one command per
    # slot, so any locally-accepted slot < upto is safe to commit
    abs_ = base[:, :, None, :] + sidx[None, None, :, None]
    ohu = (v[:, :, None, :] & (abs_ < upto[:, :, None, :])
           & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- P3: snapshot catch-up for deep laggards -----------
    # my frontier for this partition fell below the leader's window base:
    # the slots I need were recycled at the leader.  Adopt the leader's
    # partition row (log, base, execute) and KV stripe by reference.
    adopt = v & (execute < lowslot) & ~part_oh[:, :, 0, 0][..., None]
    new_rows_cmd, new_rows_com = [], []
    new_base_p, new_exec_p = [], []
    for p in range(R):
        mp = adopt[:, p]                           # (me, G)
        new_rows_cmd.append(jnp.where(
            mp[:, None, :], log_cmd[p, p][None], log_cmd[:, p]))
        new_rows_com.append(jnp.where(
            mp[:, None, :], log_commit[p, p][None], log_commit[:, p]))
        new_base_p.append(jnp.where(mp, base[p, p][None], base[:, p]))
        new_exec_p.append(jnp.where(mp, execute[p, p][None],
                                    execute[:, p]))
        stripe = (kidx % R == p)[None, :, None]
        kv = jnp.where(mp[:, None, :] & stripe, kv[p][None], kv)
    log_cmd = jnp.stack(new_rows_cmd, axis=1)
    log_commit = jnp.stack(new_rows_com, axis=1)
    base = jnp.stack(new_base_p, axis=1)
    execute = jnp.stack(new_exec_p, axis=1)
    base_own = diag(base)

    # ---------------- leader proposes in its own partition --------------
    # new slot while the pipe is healthy; retransmit the frontier slot
    # when it has stalled for retry_timeout steps (lost p2a/p2b)
    my_exec = diag(execute)                        # (ldr, G)
    retry = state["stuck"] >= cfg.retry_timeout
    can_new = next_slot - base_own < S             # window flow control
    prop_slot = jnp.where(retry, my_exec, next_slot)   # absolute
    do = can_new | retry
    prop_rel = jnp.clip(prop_slot - base_own, 0, S - 1)
    oh_p = sidx[None, :, None] == prop_rel[:, None, :]   # (ldr, S, G)
    new_cmd = encode_cmd(ridx[:, None], prop_slot)
    re_cmd = jnp.sum(jnp.where(oh_p, mine, 0), axis=1)
    prop_cmd = jnp.where(retry & (re_cmd != NO_CMD), re_cmd, new_cmd)
    # self-accept + self-ack
    wr_self = (do[:, None, :] & oh_p)[:, None] & part_oh  # (R, P, S, G)
    log_cmd = jnp.where(wr_self & ~log_commit,
                        prop_cmd[:, None, None, :], log_cmd)
    acks = acks | jnp.where(do[:, None, :] & oh_p,
                            (jnp.int32(1) << ridx)[:, None, None], 0)
    next_slot = next_slot + (do & ~retry & can_new)
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(prop_slot[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None, :], (R, R, G)),
    }

    # ---------------- execute committed prefixes, apply to KV -----------
    # each replica advances R independent frontiers; keys are partition-
    # striped (key = part + R * hash) so applies never conflict
    advanced = jnp.zeros((R, R, G), jnp.int32)
    running = jnp.ones((R, R, G), bool)
    kspace = max(K // R, 1)
    for e in range(cfg.exec_window):
        rel_e = execute + e - base                  # (rep, part, G)
        oh_e = sidx[None, None, :, None] == rel_e[:, :, None, :]
        com = jnp.any(oh_e & log_commit, axis=2)
        running = running & com
        cmd_e = jnp.sum(jnp.where(oh_e, log_cmd, 0), axis=2)
        key_e = (ridx[None, :, None] + R * fib_key(cmd_e, kspace)) % K
        wr = running & (cmd_e >= 0)
        ohk = wr[:, :, None, :] & (kidx[None, None, :, None]
                                   == key_e[:, :, None, :])
        kv = jnp.where(jnp.any(ohk, axis=1),
                       jnp.max(jnp.where(ohk, cmd_e[:, :, None, :], -1),
                               axis=1),
                       kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- stuck-frontier counter (drives retransmits) -------
    my_exec_new = diag(new_execute)
    stalled = (my_exec_new == my_exec) & (next_slot > my_exec_new)
    stuck = jnp.where(retry, 0, jnp.where(stalled, state["stuck"] + 1, 0))

    # ---------------- P3 out: newly committed or frontier retransmit ----
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :, None], S), axis=1)
    any_new = jnp.any(newly, axis=1)
    # otherwise cycle retransmits through my in-window committed prefix
    # (deep laggards are healed by snapshot adoption instead)
    span = jnp.maximum(my_exec_new - base_own, 1)
    rr = ctx.t % span
    p3_rel = jnp.where(any_new, low_new, rr).astype(jnp.int32)
    p3_rel = jnp.clip(p3_rel, 0, S - 1)
    oh_3 = sidx[None, :, None] == p3_rel[:, None, :]
    p3_committed = jnp.any(oh_3 & diag(log_commit), axis=1)
    p3_cmd = jnp.sum(jnp.where(oh_3, diag(log_cmd), 0), axis=1)
    out_p3 = {
        "valid": jnp.broadcast_to(p3_committed[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to((base_own + p3_rel)[:, None, :],
                                 (R, R, G)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None, :], (R, R, G)),
        "upto": jnp.broadcast_to(my_exec_new[:, None, :], (R, R, G)),
        "lowslot": jnp.broadcast_to(base_own[:, None, :], (R, R, G)),
    }

    # ---------------- slide the ring windows (slot recycling) -----------
    new_base = jnp.maximum(base, new_execute - RETAIN)
    adv = new_base - base                           # (rep, part, G)
    log_cmd = shift_window(log_cmd, adv, NO_CMD)
    log_commit = shift_window(log_commit, adv, False)
    acks = shift_window(acks, diag(adv), 0)

    new_state = dict(
        log_cmd=log_cmd, log_commit=log_commit, base=new_base, acks=acks,
        next_slot=next_slot, execute=new_execute, kv=kv, stuck=stuck,
    )
    outbox = {"p2a": out_p2a, "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots summed over all partitions (most advanced copy)."""
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=(0, 1))),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Agreement: committed commands for a (partition, slot) agree —
    checked on the base-aligned common window.  2. Stability: committed
    entries never change or un-commit while ring-resident; the window
    only recycles executed slots.  3. Executed prefix is committed
    (within the window)."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    # 1. agreement on the aligned window per partition
    align = jnp.max(base, axis=0)[None] - base      # (rep, part, G)
    a_c = shift_window(c, align, False)
    a_cmd = shift_window(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)   # (part, S, G)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    # 2. stability + only-executed-recycled
    adv = base - old["base"]
    o_c = shift_window(old["log_commit"], adv, False)
    o_cmd = shift_window(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    # 3. executed prefix committed (ring positions below the frontier)
    abs_ = base[:, :, None, :] + sidx[None, None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, :, None, :]) & ~c)

    return (v_agree + v_stable + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="kpaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
