"""Multi-Paxos, per-group (group-major) kernel layout — the CPU path.

The lane-major kernel in ``paxos/sim.py`` puts the group axis on the
TPU vector lanes; on the CPU backend that layout measured ~6x slower
than this per-group kernel (the runner vmaps it over a leading group
axis, which XLA:CPU vectorizes well).  ``bench.py`` and callers that
may land on CPU select this variant by backend; semantics and the
safety oracle are identical to the lane-major kernel.

Reference: paxi paxos/paxos.go — single stable leader, phase-1 ballot
election with log recovery from P1b payloads, per-slot phase-2 acceptance
under a majority quorum, P3 commit broadcast, in-order execution
(HandleRequest/HandleP1a/HandleP1b/HandleP2a/HandleP2b/HandleP3) [driver].

TPU re-design (not a translation):
- Per-replica state is a struct-of-arrays over a fixed **ring** of S
  slots with a *fixed cell mapping*: absolute slot ``a`` always lives
  in cell ``a % S``.  The window ``[base, base + S)`` slides forward as
  the execute frontier advances, retaining the last ``S//2`` executed
  slots for laggard healing (the reference's unbounded
  ``log map[int]*entry`` becomes O(window) — 10M slots run in a
  64-slot ring).  Because the mapping is position-invariant, sliding
  the window is a masked *clear* of recycled cells — no data movement —
  and any two replicas' cells line up without per-pair realignment
  gathers: cell ``c`` refers to the same absolute slot at replicas
  ``x`` and ``y`` exactly when that slot is inside both windows.  (An
  earlier revision kept ring position 0 at ``base`` and paid 13
  per-row shift gathers per step — ~40% of north-star bench wall time
  on XLA:CPU, where gathers scalarize.)
- All handlers run every step on every replica as fully *masked*
  updates (leader/follower divergence is `where`-selected).
- Ballots are ``round * ballot_stride + replica_idx`` int32s
  (paxos ballot.go packs n<<16|id the same way).
- ``Quorum.ACK`` becomes a **bit-packed int32 ack mask** with
  ``lax.population_count`` for ``Majority()`` (quorum.go [driver]) —
  ``p1_acks (R,)``, ``log_acks (R, S)``, bit ``src`` = ack from that
  replica.  (Same packing as the lane-major kernel; the earlier
  boolean ``(R, S, R)`` planes dominated the window-slide cost.)
- Messages carry ABSOLUTE slot numbers; receivers mask them against
  their own window (out-of-window = silently ignored, like a TCP
  segment for a closed connection).
- P1b log payloads are passed *by reference*: on winning phase-1 the
  new leader merges the current logs of its ackers — with the fixed
  cell mapping this is a pure elementwise masked reduction over the
  ``(ldr, src, S)`` ack cube (no gathers).  A laggard winner first
  adopts the most advanced acker's (kv, execute, base) — the state-
  transfer/log-compaction analog of the host runtime's P1b snapshot.
- P3 carries (slot, cmd) plus a commit frontier ``upto``: a follower
  commits any in-window slot < upto accepted at the leader's exact
  ballot.  A follower whose frontier fell below the leader's window
  base adopts the leader's (kv, execute, base) wholesale (snapshot
  catch-up) and keeps any of its own still-in-window commits.
- Client load: the leader proposes one new command per step while the
  window has room (closed-loop stream with window flow control);
  commands encode (ballot, slot) so the agreement oracle can detect
  any two-leaders-two-values divergence.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.hashing import fib_key  # noqa: F401 (re-export parity)
# one definition of the wire/command encoding for both layouts — a tweak
# to either must reach the parity test and the bench backend switch
from paxi_tpu.protocols.paxos.sim import (NO_CMD, NOOP, cmd_key,
                                          encode_cmd, mailbox_spec)
from paxi_tpu.sim import inscan
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx
from paxi_tpu.workload import compile as wlc
from paxi_tpu.workload.spec import CLASSES


def _cell_abs(base, S: int):
    """The absolute slot cell ``c`` currently holds at each replica:
    the unique element of ``[base_r, base_r + S)`` congruent to ``c``
    (mod S).  Pure elementwise — the fixed-mapping replacement for the
    old shift-to-ring-position bookkeeping."""
    sidx = jnp.arange(S, dtype=jnp.int32)
    return base[:, None] + jnp.remainder(sidx[None, :] - base[:, None], S)


def init_state(cfg: SimConfig, rng: jax.Array):
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    del rng
    require_packable(R)   # ack bitmasks: int32 shifts wrap at 32
    st = dict(
        ballot=jnp.zeros((R,), jnp.int32),        # highest ballot seen/promised
        active=jnp.zeros((R,), bool),             # leader with phase-1 done
        p1_acks=jnp.zeros((R,), jnp.int32),       # [ldr] phase-1 ack bitmask
        base=jnp.zeros((R,), jnp.int32),          # window start (absolute)
        log_bal=jnp.zeros((R, S), jnp.int32),     # accepted ballot per slot
        log_cmd=jnp.full((R, S), NO_CMD, jnp.int32),
        log_commit=jnp.zeros((R, S), bool),
        log_acks=jnp.zeros((R, S), jnp.int32),    # [ldr, slot] P2b ack bitmask
        proposed=jnp.zeros((R, S), bool),         # P2a sent under my ballot
        next_slot=jnp.zeros((R,), jnp.int32),     # absolute
        execute=jnp.zeros((R,), jnp.int32),       # absolute frontier
        kv=jnp.zeros((R, K), jnp.int32),
        # replica 0's timer fires at step 0 => immediate first election
        timer=jnp.arange(R, dtype=jnp.int32) * cfg.election_timeout,
        stuck=jnp.zeros((R,), jnp.int32),         # frontier-stall counter
        # ---- on-device observability (``m_`` planes: excluded from
        # the witness hash, never read by protocol logic — PXM10x).
        # Per-group layout: the histogram is (N_BUCKETS,), the
        # accumulators scalars; the runner's vmap gives them their
        # group axis.  Same semantics as the lane-major kernel.
        m_prop_t=jnp.zeros((R, S), jnp.int32),
        # pending propose->commit deltas: the step stores each newly
        # committed cell's delta here (one masked write); the RUNNER
        # bins them into m_lat_hist every flush_every(S) steps under a
        # batch-level lax.cond (sim/runner.flush_measurements) — the
        # N_BUCKETS reduction fan is off the per-step hot path, which
        # is what keeps the 100k-group bench overhead single-digit
        m_commit_dt=jnp.zeros((R, S), jnp.int32),
        m_lat_hist=lathist.empty_hist(),
        m_lat_sum=jnp.zeros((), jnp.int32),
        m_inscan_viol=jnp.zeros((), jnp.int32),
    )
    if cfg.workload is not None:
        # GLOBAL group id — a scalar here; the runner's per-group vmap
        # branch patches the vmapped plane to arange(n_groups) so the
        # workload's counter-based draws key on the same (group,
        # absolute slot) pairs as the lane-major lowering (bit-for-bit
        # parity).  NOT m_-prefixed (feeds key derivation).
        st["wl_gid"] = jnp.zeros((), jnp.int32)
        # per-key-class commit-latency planes (hot/warm/cold), binned
        # directly at commit — mirrors the lane-major kernel; the vmap
        # gives them their group axis
        for nm in CLASSES:
            st[f"m_wl_hist_{nm}"] = lathist.empty_hist()
            st[f"m_wl_sum_{nm}"] = jnp.zeros((), jnp.int32)
    return st


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    BIG = jnp.int32(2**30)
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    bit = jnp.int32(1) << ridx                    # ack bit per source

    ballot = state["ballot"]
    active = state["active"]
    p1_acks = state["p1_acks"]
    base = state["base"]
    log_bal = state["log_bal"]
    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    log_acks = state["log_acks"]
    proposed = state["proposed"]
    next_slot = state["next_slot"]
    execute = state["execute"]
    kv = state["kv"]
    m_prop_t = state["m_prop_t"]
    m_lat_hist = state["m_lat_hist"]
    m_lat_sum = state["m_lat_sum"]

    # ---------------- P1a: promise to the highest proposer --------------
    m = inbox["p1a"]
    b_in = jnp.where(m["valid"], m["bal"], 0)            # (src, dst)
    p1a_bal = jnp.max(b_in, axis=0)                      # per dst
    p1a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    promote = p1a_bal > ballot
    ballot = jnp.maximum(ballot, p1a_bal)
    active = active & ~promote
    p1_acks = jnp.where(promote, 0, p1_acks)             # my old round died
    # P1b out (log payload by reference; see module docstring)
    p1b_valid = promote[:, None] & (ridx[None, :] == p1a_src[:, None])
    out_p1b = {"valid": p1b_valid,
               "bal": jnp.broadcast_to(ballot[:, None], (R, R))}

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx)

    # ---------------- P1b: collect phase-1 acks -------------------------
    m = inbox["p1b"]
    ack = m["valid"].T & (m["bal"].T == ballot[:, None]) & own_bal[:, None]
    p1_acks = p1_acks | jnp.sum(jnp.where(ack, bit[None, :], 0), axis=1)
    p1_win = own_bal & ~active & \
        (jax.lax.population_count(p1_acks) >= MAJ)
    amask = (p1_acks[:, None] >> ridx[None, :]) & 1 != 0  # (ldr, src) w/ self

    # ---------------- phase-1 win: state transfer from best acker -------
    # A laggard winner's window may sit below its ackers' windows; adopt
    # the most advanced acker's (kv, execute, base) first — by-reference
    # equivalent of the host runtime's P1b (execute, snapshot) transfer.
    exec_am = jnp.where(amask, execute[None, :], -1)      # (ldr, src)
    f_src = jnp.argmax(exec_am, axis=1).astype(jnp.int32)
    front = jnp.max(exec_am, axis=1)
    el_ad = p1_win & (front > execute)
    kv = jnp.where(el_ad[:, None], kv[f_src], kv)
    execute = jnp.where(el_ad, front, execute)
    next_slot = jnp.where(el_ad, jnp.maximum(next_slot, front), next_slot)
    # never adopt a LOWER base: dropping my own top-of-window entries
    # (possibly committed via P3) is never safe.  The merge below
    # tolerates ackers whose base is below mine (front-fill only).
    A_old = _cell_abs(base, S)
    base = jnp.where(el_ad, jnp.maximum(base[f_src], base), base)
    # recycled cells (abs slot now below the adopted base) reset in
    # place — the fixed mapping's no-copy equivalent of the old shift
    drop = A_old < base[:, None]
    log_bal = jnp.where(drop, 0, log_bal)
    log_cmd = jnp.where(drop, NO_CMD, log_cmd)
    log_commit = log_commit & ~drop
    proposed = proposed & ~drop
    log_acks = jnp.where(drop, 0, log_acks)
    m_prop_t = jnp.where(drop, 0, m_prop_t)

    # ---------------- phase-1 win: merge ackers' logs -------------------
    # Fixed cell mapping: leader cell c and acker cell c hold the SAME
    # absolute slot exactly when the leader's slot A[l, c] is inside the
    # acker's window — a pure mask, no base-alignment gather.
    A = _cell_abs(base, S)
    Al = A[:, None, :]                                    # (ldr, 1, S)
    in_src = (Al >= base[None, :, None]) & (Al < base[None, :, None] + S)
    sel = amask[:, :, None] & in_src                      # (ldr, src, S)
    lb = jnp.where(sel, log_bal[None], -1)
    src_best = jnp.argmax(lb, axis=1)                     # (ldr, S)
    best_bal = jnp.max(lb, axis=1)
    oh_best = ridx[None, :, None] == src_best[:, None, :]
    merged_cmd = jnp.sum(jnp.where(oh_best, log_cmd[None], 0), axis=1)
    cmask = sel & log_commit[None]
    merged_commit = jnp.any(cmask, axis=1)                # (ldr, S)
    csrc = jnp.argmax(cmask, axis=1)
    oh_csrc = ridx[None, :, None] == csrc[:, None, :]
    committed_cmd = jnp.sum(jnp.where(oh_csrc, log_cmd[None], 0), axis=1)
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, A + 1, 0), axis=1)   # (ldr,) absolute
    new_next = jnp.maximum(next_slot, top)
    in_win = A < new_next[:, None]                        # slots to own
    w = p1_win[:, None]
    # committed slots adopt the committed value; accepted adopt merged;
    # holes below the frontier become NOOP re-proposals.
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    log_cmd = jnp.where(w & in_win, adopt_cmd, log_cmd)
    log_bal = jnp.where(w & in_win, ballot[:, None], log_bal)
    log_commit = jnp.where(w & in_win, merged_commit | log_commit, log_commit)
    proposed = jnp.where(w, in_win & (merged_commit | log_commit), proposed)
    log_acks = jnp.where(w, jnp.where(in_win, bit[:, None], 0), log_acks)
    next_slot = jnp.where(p1_win, new_next, next_slot)
    active = active | p1_win
    # a takeover restarts the adopted slots' latency clocks
    m_prop_t = jnp.where(w & proposed & (m_prop_t == 0), ctx.t, m_prop_t)

    # ---------------- P2a: accept from the highest-ballot leader --------
    m = inbox["p2a"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)    # per dst
    a_bal = jnp.max(b_in, axis=0)
    a_has = a_bal > 0
    a_slot = m["slot"][a_src, ridx]                       # absolute
    a_cmd = m["cmd"][a_src, ridx]
    acc_ok = a_has & (a_bal >= ballot)
    demote = acc_ok & (a_bal > ballot)                    # someone else leads
    ballot = jnp.where(acc_ok, a_bal, ballot)
    active = active & ~demote
    p1_acks = jnp.where(demote, 0, p1_acks)
    a_inw = (a_slot >= base) & (a_slot < base + S)
    oh = (acc_ok & a_inw)[:, None] & \
        (sidx[None, :] == jnp.remainder(a_slot, S)[:, None])
    writable = oh & (log_bal <= a_bal[:, None]) & ~log_commit
    log_bal = jnp.where(writable, a_bal[:, None], log_bal)
    log_cmd = jnp.where(writable, a_cmd[:, None], log_cmd)
    # ack ONLY what we durably stored: a slot outside our window was
    # dropped, and acking it would let the leader commit an entry no
    # majority actually holds (lost acceptance after a leader change)
    out_p2b = {
        "valid": (acc_ok & a_inw)[:, None] & (ridx[None, :] == a_src[:, None]),
        "bal": jnp.broadcast_to(a_bal[:, None], (R, R)),
        "slot": jnp.broadcast_to(a_slot[:, None], (R, R)),
    }

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx)

    # ---------------- P2b: leader tallies acks, commits -----------------
    m = inbox["p2b"]
    okb = m["valid"].T & (m["bal"].T == ballot[:, None]) & \
        (active & own_bal)[:, None]                       # (ldr, src)
    bslot = m["slot"].T                                   # (ldr, src) absolute
    okb = okb & (bslot >= base[:, None]) & (bslot < base[:, None] + S)
    oh3 = okb[:, :, None] & \
        (sidx[None, None, :] == jnp.remainder(bslot, S)[:, :, None])
    log_acks = log_acks | jnp.sum(
        jnp.where(oh3, bit[None, :, None], 0), axis=1)    # (ldr, slot)
    acks_n = jax.lax.population_count(log_acks)
    newly = ((active & own_bal)[:, None] & (acks_n >= MAJ)
             & ~log_commit & (log_cmd != NO_CMD) & proposed)
    log_commit = log_commit | newly
    # in-kernel commit latency: store every newly committed (leader,
    # slot)'s propose->commit step delta into the pending plane — the
    # runner's deferred flush log2-bins it into m_lat_hist (see
    # init_state); the pending plane survives recycling/adoption
    # untouched, its flush period is shorter than any cell-reuse cycle
    lat_dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_commit_dt = jnp.where(newly, lat_dt, state["m_commit_dt"])
    m_lat_sum = m_lat_sum + jnp.sum(jnp.where(newly, lat_dt, 0),
                                    dtype=jnp.int32)
    # per-key-class latency (workload runs): the committed cell's key
    # class derives from (group, absolute slot) — same counter draw as
    # the executor's key id — mirroring the lane-major kernel
    wl = cfg.workload
    wl_planes = {}
    if wl is not None:
        gid = state["wl_gid"]                             # scalar group id
        clsP = wlc.class_plane(wl, K, gid, A)             # (R, S)
        for ci, nm in enumerate(CLASSES):
            cm = newly & (clsP == ci)
            wl_planes[f"m_wl_hist_{nm}"] = lathist.hist_update(
                state[f"m_wl_hist_{nm}"], lat_dt, cm)
            wl_planes[f"m_wl_sum_{nm}"] = state[f"m_wl_sum_{nm}"] \
                + jnp.sum(jnp.where(cm, lat_dt, 0), dtype=jnp.int32)
        wl_planes["wl_gid"] = gid

    # ---------------- P3: commit notifications --------------------------
    # Zombie fences (see sim/ballot_ring.py apply_p3): a higher-ballot
    # P3 deposes the receiver, and the frontier commit only fires for
    # bal >= my promised ballot — a deposed leader partitioned through
    # later rounds must not commit never-chosen same-stale-ballot
    # entries at fellow laggards via its post-adoption upto.
    m = inbox["p3"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    c_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    c_bal = jnp.max(b_in, axis=0)
    c_has = c_bal > 0
    c_slot = m["slot"][c_src, ridx]                       # absolute
    c_cmd = m["cmd"][c_src, ridx]
    c_upto = m["upto"][c_src, ridx]
    fresh3 = c_has & (c_bal >= ballot)
    promote3 = c_has & (c_bal > ballot)
    ballot = jnp.where(promote3, c_bal, ballot)
    active = active & ~promote3
    p1_acks = jnp.where(promote3, 0, p1_acks)
    c_inw = (c_slot >= base) & (c_slot < base + S)
    oh = (c_has & c_inw)[:, None] & \
        (sidx[None, :] == jnp.remainder(c_slot, S)[:, None])
    log_cmd = jnp.where(oh, c_cmd[:, None], log_cmd)
    log_bal = jnp.where(oh, jnp.maximum(log_bal, c_bal[:, None]), log_bal)
    log_commit = log_commit | oh
    # frontier commit: slots < upto accepted at the leader's exact ballot
    ohu = (fresh3[:, None] & (A < c_upto[:, None])
           & (log_bal == c_bal[:, None]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- P3: snapshot catch-up for deep laggards -----------
    # My frontier fell below the sender's window base: the slots I still
    # need were recycled everywhere ahead of me.  Adopt the sender's
    # (kv, execute, base) by reference and keep my own in-window commits
    # — under the fixed mapping the sender's cells are already aligned
    # with mine, so the overlay is elementwise.
    src_base = base[c_src]
    adopt = c_has & (execute < src_base)
    keep = A >= src_base[:, None]            # my cells still in the new window
    my_bal = jnp.where(keep, log_bal, 0)
    my_cmd = jnp.where(keep, log_cmd, NO_CMD)
    my_com = keep & log_commit
    s_bal, s_cmd, s_com = log_bal[c_src], log_cmd[c_src], log_commit[c_src]
    a2 = adopt[:, None]
    log_bal = jnp.where(a2, jnp.where(s_com, s_bal, my_bal), log_bal)
    log_cmd = jnp.where(a2, jnp.where(s_com, s_cmd, my_cmd), log_cmd)
    log_commit = jnp.where(a2, s_com | my_com, log_commit)
    proposed = jnp.where(a2, False, proposed)
    log_acks = jnp.where(a2, 0, log_acks)
    m_prop_t = jnp.where(a2, 0, m_prop_t)
    kv = jnp.where(a2, kv[c_src], kv)
    execute = jnp.where(adopt, execute[c_src], execute)
    next_slot = jnp.where(adopt, jnp.maximum(next_slot, execute), next_slot)
    base = jnp.where(adopt, src_base, base)
    A = _cell_abs(base, S)

    # ---------------- leader proposes (new cmd or re-proposal) ----------
    is_leader = active & own_bal
    mask_re = (~log_commit) & (~proposed) & (A < next_slot[:, None])
    re_abs = jnp.min(jnp.where(mask_re, A, BIG), axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = (next_slot - base) < S                      # window flow control
    if wl is not None:
        # flash-crowd demand gate on NEW commands only; re-proposals
        # always proceed (gating recovery would be a liveness bug)
        gate = wlc.demand_gate(wl, state["wl_gid"], ctx.t)
        if gate is not None:
            can_new = can_new & gate
    prop_slot = jnp.where(has_re, re_abs, next_slot)      # absolute
    prop_cell = jnp.remainder(prop_slot, S)
    is_new = ~has_re & can_new
    new_cmd = encode_cmd(ballot, prop_slot)
    re_cmd = jnp.take_along_axis(log_cmd, prop_cell[:, None], axis=1)[:, 0]
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    prop_cmd = jnp.where(is_new, new_cmd, re_cmd)
    do = is_leader & (has_re | can_new)
    oh = do[:, None] & (sidx[None, :] == prop_cell[:, None])
    log_bal = jnp.where(oh, ballot[:, None], log_bal)
    log_cmd = jnp.where(oh & ~log_commit, prop_cmd[:, None], log_cmd)
    # latency clock: a slot's FIRST propose starts it (re-proposals and
    # go-back-N retries keep the original start — honest end-to-end
    # commit latency; recycled cells re-arm via the drop clears)
    m_prop_t = jnp.where(oh & ~proposed & (m_prop_t == 0),
                         ctx.t, m_prop_t)
    proposed = proposed | oh
    log_acks = log_acks | jnp.where(oh, bit[:, None], 0)  # self ack
    next_slot = next_slot + (is_new & do)
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
        "slot": jnp.broadcast_to(prop_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None], (R, R)),
    }

    # ---------------- execute committed prefix, apply to KV -------------
    # one fused gather over the exec window, then masked KV writes
    E = cfg.exec_window
    absE = execute[:, None] + jnp.arange(E, dtype=jnp.int32)[None, :]
    inbE = absE < base[:, None] + S                       # execute >= base
    cellE = jnp.remainder(absE, S)
    comE = jnp.take_along_axis(log_commit, cellE, axis=1) & inbE
    cmdE = jnp.take_along_axis(log_cmd, cellE, axis=1)
    running = jnp.cumprod(comE, axis=1).astype(bool)      # (R, E) prefix
    advanced = jnp.sum(running, axis=1).astype(jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)
    for e in range(E):
        cmd_e = cmdE[:, e]
        if wl is None:
            key_e = cmd_key(cmd_e, K)
            wr = running[:, e] & (cmd_e >= 0)
        else:
            # workload command plane: key id + read flag derive from
            # (global group id, absolute slot) — identical at every
            # replica and every layout; reads advance the frontier
            # but never write the KV
            key_e = wlc.key_plane(wl, K, state["wl_gid"], absE[:, e])
            wr = running[:, e] & (cmd_e >= 0) \
                & ~wlc.read_plane(wl, state["wl_gid"], absE[:, e])
        ohk = wr[:, None] & (kidx[None, :] == key_e[:, None])
        kv = jnp.where(ohk, cmd_e[:, None], kv)
    new_execute = execute + advanced

    # ---------------- P3 out: newly committed + frontier retransmit -----
    low_new = jnp.min(jnp.where(newly, A, BIG), axis=1)   # lowest abs slot
    any_new = jnp.any(newly, axis=1)
    # otherwise cycle retransmits through my in-window committed prefix
    # (laggards behind the window are healed by snapshot adoption)
    span = jnp.maximum(new_execute - base, 1)
    rr = ctx.t % span
    p3_abs = jnp.where(any_new, low_new, base + rr)
    p3_cell = jnp.remainder(p3_abs, S)
    p3_committed = jnp.take_along_axis(
        log_commit, p3_cell[:, None], axis=1)[:, 0]
    p3_cmd = jnp.take_along_axis(log_cmd, p3_cell[:, None], axis=1)[:, 0]
    p3_do = is_leader & p3_committed
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
        "slot": jnp.broadcast_to(p3_abs[:, None], (R, R)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None], (R, R)),
        "upto": jnp.broadcast_to(new_execute[:, None], (R, R)),
    }

    # ---------------- stuck-frontier retry (lost P2a/P2b) ---------------
    stalled = is_leader & (new_execute == execute) & (next_slot > new_execute)
    stuck = jnp.where(stalled, state["stuck"] + 1, 0)
    retry = stuck >= cfg.retry_timeout
    # retry implies next_slot > new_execute, so the frontier is in-window
    ohr = retry[:, None] & \
        (sidx[None, :] == jnp.remainder(new_execute, S)[:, None])
    proposed = proposed & ~ohr
    stuck = jnp.where(retry, 0, stuck)

    # ---------------- election timer ------------------------------------
    heard = promote | acc_ok | (c_has & (c_bal >= ballot))
    k_jit = jr.fold_in(ctx.rng, 17)
    jitter = jr.randint(k_jit, (R,), 0, cfg.backoff + 1)
    timer = jnp.where(heard | active,
                      cfg.election_timeout + jitter,
                      state["timer"] - 1)
    fire = ~active & (timer <= 0)
    new_bal = (jnp.max(ballot) // STRIDE + 1) * STRIDE + ridx
    ballot = jnp.where(fire, new_bal, ballot)
    p1_acks = jnp.where(fire, bit, p1_acks)               # self-ack only
    timer = jnp.where(fire, cfg.election_timeout + jitter, timer)
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
    }

    # ---------------- slide the ring window (slot recycling) ------------
    # keep the last RETAIN executed slots resident for P3 retransmits;
    # anything older is only reachable via snapshot adoption.  Fixed
    # mapping: recycled cells are cleared in place, nothing moves.
    new_base = jnp.maximum(base, new_execute - RETAIN)
    drop = A < new_base[:, None]
    log_bal = jnp.where(drop, 0, log_bal)
    log_cmd = jnp.where(drop, NO_CMD, log_cmd)
    log_commit = log_commit & ~drop
    proposed = proposed & ~drop
    log_acks = jnp.where(drop, 0, log_acks)
    m_prop_t = jnp.where(drop, 0, m_prop_t)

    # in-scan linearizability spot-check (sim/inscan): an independent
    # oracle beside invariants(), accumulated on device
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], new_execute, state["base"], new_base,
        _cell_abs(state["base"], S), _cell_abs(new_base, S),
        state["log_cmd"], log_cmd,
        state["log_commit"], log_commit,
        kv=kv, lane_major=False)

    new_state = dict(
        ballot=ballot, active=active, p1_acks=p1_acks, base=new_base,
        log_bal=log_bal, log_cmd=log_cmd, log_commit=log_commit,
        log_acks=log_acks, proposed=proposed, next_slot=next_slot,
        execute=new_execute, kv=kv, timer=timer, stuck=stuck,
        m_prop_t=m_prop_t, m_commit_dt=m_commit_dt,
        m_lat_hist=m_lat_hist, m_lat_sum=m_lat_sum,
        m_inscan_viol=m_inscan_viol,
        **wl_planes,
    )
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots = executed prefix at the most advanced replica
    (executed implies committed and agreement-checked)."""
    return {
        "committed_slots": jnp.max(state["execute"]),
        "min_execute": jnp.min(state["execute"]),
        "has_leader": jnp.any(state["active"]).astype(jnp.int32),
        # observability scalars (the histogram itself rides in state
        # as m_lat_hist; a vector would not survive the per-group
        # metric summation).  The sample count includes deltas still
        # pending the runner's deferred flush.
        "commit_lat_sum": state["m_lat_sum"],
        "commit_lat_n": (jnp.sum(state["m_lat_hist"])
                         + jnp.sum((state["m_commit_dt"] > 0)
                                   .astype(jnp.int32))),
        "inscan_violations": state["m_inscan_viol"],
        # per-key-class sample counts (workload runs; the full
        # per-class histograms ride in state — workload.class_split)
        **{f"wl_{nm}_n": jnp.sum(state[f"m_wl_hist_{nm}"])
           for nm in CLASSES if f"m_wl_hist_{nm}" in state},
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Per-step safety oracle (generalizes history.go's checker):
    1. Agreement: all committed commands for a slot are equal — checked
       on the common window across replicas (cells align under the
       fixed mapping, so this is a masked elementwise compare).
    2. Stability: a committed (slot, cmd) never changes or un-commits
       while it remains in the window; slots recycled out must have
       been executed (execute >= base always).
    3. Ballot monotonicity per replica.
    4. Executed prefix is committed (within the window)."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]
    A = _cell_abs(base, S)

    # 1. agreement on the common window [max(base), max(base)+S): cell
    # c refers to the same absolute slot at every replica whose window
    # contains it (all in-window abs values are congruent mod S)
    vis = c & (A >= jnp.max(base))
    mx = jnp.max(jnp.where(vis, cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(vis, cmd, BIG), axis=0)
    n_c = jnp.sum(vis, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    # 2. stability: old commits still in-window live in the SAME cell
    # (fixed mapping) and must match; the window may only recycle
    # executed slots (base <= execute)
    o_c = old["log_commit"] & (_cell_abs(old["base"], S) >= base[:, None])
    v_stable = jnp.sum(o_c & (~c | (cmd != old["log_cmd"])))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    # 3. ballot monotonicity
    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    # 4. executed prefix committed (slots below the frontier)
    v_exec = jnp.sum((A < new["execute"][:, None]) & ~c)

    return (v_agree + v_stable + v_bal + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="paxos_pg",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
)
