"""Multi-Paxos, per-group (group-major) kernel layout — the CPU path.

The lane-major kernel in ``paxos/sim.py`` puts the group axis on the
TPU vector lanes; on the CPU backend that layout measured ~6x slower
than this per-group kernel (the runner vmaps it over a leading group
axis, which XLA:CPU vectorizes well).  ``bench.py`` and callers that
may land on CPU select this variant by backend; semantics and the
safety oracle are identical to the lane-major kernel.

Reference: paxi paxos/paxos.go — single stable leader, phase-1 ballot
election with log recovery from P1b payloads, per-slot phase-2 acceptance
under a majority quorum, P3 commit broadcast, in-order execution
(HandleRequest/HandleP1a/HandleP1b/HandleP2a/HandleP2b/HandleP3) [driver].

TPU re-design (not a translation):
- Per-replica state is a struct-of-arrays over a fixed **ring** of S
  slots: ring position ``i`` holds absolute slot ``base + i``; the
  window slides forward as the execute frontier advances, retaining the
  last ``S//2`` executed slots for laggard healing (the reference's
  unbounded ``log map[int]*entry`` becomes O(window), the SURVEY §7
  slot-recycling requirement — 10M slots run in a 64-slot ring).
- All handlers run every step on every replica as fully *masked*
  updates (leader/follower divergence is `where`-selected).
- Ballots are ``round * ballot_stride + replica_idx`` int32s
  (paxos ballot.go packs n<<16|id the same way).
- ``Quorum.ACK`` becomes a boolean ack-matrix OR + popcount
  (p1_acks (R,R); log_acks (R,S,R)) [driver].
- Messages carry ABSOLUTE slot numbers; receivers mask them against
  their own window (out-of-window = silently ignored, like a TCP
  segment for a closed connection).
- P1b log payloads are passed *by reference*: on winning phase-1 the
  new leader merges the current logs of its ackers, base-aligned via a
  per-(leader, acker) gather.  A laggard winner first adopts the most
  advanced acker's (kv, execute, base) — the state-transfer/log-
  compaction analog of the host runtime's P1b snapshot.
- P3 carries (slot, cmd) plus a commit frontier ``upto``: a follower
  commits any in-window slot < upto accepted at the leader's exact
  ballot.  A follower whose frontier fell below the leader's window
  base adopts the leader's (kv, execute, base) wholesale (snapshot
  catch-up) and keeps any of its own still-in-window commits.
- Client load: the leader proposes one new command per step while the
  window has room (closed-loop stream with window flow control);
  commands encode (ballot, slot) so the agreement oracle can detect
  any two-leaders-two-values divergence.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.ops.hashing import fib_key  # noqa: F401 (re-export parity)
# one definition of the wire/command encoding for both layouts — a tweak
# to either must reach the parity test and the bench backend switch
from paxi_tpu.protocols.paxos.sim import (NO_CMD, NOOP, cmd_key,
                                          encode_cmd, mailbox_spec)
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx


def _shift(arr, adv, fill):
    """Slide rows of ``arr`` (R, S, ...) forward along the slot axis by
    per-row ``adv`` >= 0: out[r, i] = arr[r, i + adv[r]] (or ``fill``
    past the end).  The ring-recycling / base-alignment primitive."""
    S = arr.shape[1]
    idx = jnp.arange(S, dtype=jnp.int32)[None, :] + adv[:, None]
    valid = (idx >= 0) & (idx < S)
    idxc = jnp.clip(idx, 0, S - 1)
    if arr.ndim == 2:
        return jnp.where(valid, jnp.take_along_axis(arr, idxc, axis=1), fill)
    return jnp.where(valid[:, :, None],
                     jnp.take_along_axis(arr, idxc[:, :, None], axis=1),
                     fill)


def init_state(cfg: SimConfig, rng: jax.Array):
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    del rng
    return dict(
        ballot=jnp.zeros((R,), jnp.int32),        # highest ballot seen/promised
        active=jnp.zeros((R,), bool),             # leader with phase-1 done
        p1_acks=jnp.zeros((R, R), bool),          # [ldr, src] phase-1 acks
        base=jnp.zeros((R,), jnp.int32),          # abs slot of ring pos 0
        log_bal=jnp.zeros((R, S), jnp.int32),     # accepted ballot per slot
        log_cmd=jnp.full((R, S), NO_CMD, jnp.int32),
        log_commit=jnp.zeros((R, S), bool),
        log_acks=jnp.zeros((R, S, R), bool),      # [ldr, slot, src] P2b acks
        proposed=jnp.zeros((R, S), bool),         # P2a sent under my ballot
        next_slot=jnp.zeros((R,), jnp.int32),     # absolute
        execute=jnp.zeros((R,), jnp.int32),       # absolute frontier
        kv=jnp.zeros((R, K), jnp.int32),
        # replica 0's timer fires at step 0 => immediate first election
        timer=jnp.arange(R, dtype=jnp.int32) * cfg.election_timeout,
        stuck=jnp.zeros((R,), jnp.int32),         # frontier-stall counter
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)

    ballot = state["ballot"]
    active = state["active"]
    p1_acks = state["p1_acks"]
    base = state["base"]
    log_bal = state["log_bal"]
    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    log_acks = state["log_acks"]
    proposed = state["proposed"]
    next_slot = state["next_slot"]
    execute = state["execute"]
    kv = state["kv"]

    # ---------------- P1a: promise to the highest proposer --------------
    m = inbox["p1a"]
    b_in = jnp.where(m["valid"], m["bal"], 0)            # (src, dst)
    p1a_bal = jnp.max(b_in, axis=0)                      # per dst
    p1a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    promote = p1a_bal > ballot
    ballot = jnp.maximum(ballot, p1a_bal)
    active = active & ~promote
    p1_acks = jnp.where(promote[:, None], False, p1_acks)  # my old round died
    # P1b out (log payload by reference; see module docstring)
    p1b_valid = promote[:, None] & (ridx[None, :] == p1a_src[:, None])
    out_p1b = {"valid": p1b_valid,
               "bal": jnp.broadcast_to(ballot[:, None], (R, R))}

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx)

    # ---------------- P1b: collect phase-1 acks -------------------------
    m = inbox["p1b"]
    ack = m["valid"].T & (m["bal"].T == ballot[:, None]) & own_bal[:, None]
    p1_acks = p1_acks | ack                               # (ldr, src)
    p1_win = own_bal & ~active & (jnp.sum(p1_acks, axis=1) >= MAJ)
    amask = p1_acks                                       # includes self

    # ---------------- phase-1 win: state transfer from best acker -------
    # A laggard winner's window may sit below its ackers' windows; adopt
    # the most advanced acker's (kv, execute, base) first — by-reference
    # equivalent of the host runtime's P1b (execute, snapshot) transfer.
    exec_am = jnp.where(amask, execute[None, :], -1)      # (ldr, src)
    f_src = jnp.argmax(exec_am, axis=1).astype(jnp.int32)
    front = jnp.max(exec_am, axis=1)
    el_ad = p1_win & (front > execute)
    kv = jnp.where(el_ad[:, None], kv[f_src], kv)
    execute = jnp.where(el_ad, front, execute)
    next_slot = jnp.where(el_ad, jnp.maximum(next_slot, front), next_slot)
    # never adopt a LOWER base: a negative self-shift would drop my own
    # top-of-window entries (possibly committed via P3).  The merge below
    # tolerates ackers whose base is below mine (front-fill only).
    adv_el = jnp.where(el_ad, jnp.maximum(base[f_src] - base, 0), 0)
    base = jnp.where(el_ad, jnp.maximum(base[f_src], base), base)
    log_bal = _shift(log_bal, adv_el, 0)
    log_cmd = _shift(log_cmd, adv_el, NO_CMD)
    log_commit = _shift(log_commit, adv_el, False)
    proposed = _shift(proposed, adv_el, False)
    log_acks = _shift(log_acks, adv_el, False)

    # ---------------- phase-1 win: merge ackers' logs (base-aligned) ----
    # leader ring pos j <-> abs base[ldr]+j <-> acker ring pos j+off
    off = base[:, None] - base[None, :]                   # (ldr, src)
    idx3 = sidx[None, None, :] + off[:, :, None]          # (ldr, src, S)
    valid3 = (idx3 >= 0) & (idx3 < S)
    idx3c = jnp.clip(idx3, 0, S - 1)
    lb_src = jnp.take_along_axis(
        jnp.broadcast_to(log_bal[None], (R, R, S)), idx3c, axis=2)
    lc_src = jnp.take_along_axis(
        jnp.broadcast_to(log_cmd[None], (R, R, S)), idx3c, axis=2)
    lm_src = jnp.take_along_axis(
        jnp.broadcast_to(log_commit[None], (R, R, S)), idx3c, axis=2)
    sel = amask[:, :, None] & valid3
    lb = jnp.where(sel, lb_src, -1)
    src_best = jnp.argmax(lb, axis=1)                     # (ldr, S)
    best_bal = jnp.max(lb, axis=1)
    merged_cmd = jnp.take_along_axis(
        lc_src, src_best[:, None, :], axis=1)[:, 0, :]
    cmask = sel & lm_src
    merged_commit = jnp.any(cmask, axis=1)                # (ldr, S)
    csrc = jnp.argmax(cmask, axis=1)
    committed_cmd = jnp.take_along_axis(
        lc_src, csrc[:, None, :], axis=1)[:, 0, :]
    abs_ = base[:, None] + sidx[None, :]                  # (R, S)
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, abs_ + 1, 0), axis=1)  # (ldr,) absolute
    new_next = jnp.maximum(next_slot, top)
    in_win = abs_ < new_next[:, None]                     # slots to own
    w = p1_win[:, None]
    # committed slots adopt the committed value; accepted adopt merged;
    # holes below the frontier become NOOP re-proposals.
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    log_cmd = jnp.where(w & in_win, adopt_cmd, log_cmd)
    log_bal = jnp.where(w & in_win, ballot[:, None], log_bal)
    log_commit = jnp.where(w & in_win, merged_commit | log_commit, log_commit)
    proposed = jnp.where(w, in_win & (merged_commit | log_commit), proposed)
    self_only = (ridx[None, None, :] == ridx[:, None, None])  # (R,1->S,R)
    log_acks = jnp.where(w[:, :, None],
                         in_win[:, :, None] & self_only, log_acks)
    next_slot = jnp.where(p1_win, new_next, next_slot)
    active = active | p1_win

    # ---------------- P2a: accept from the highest-ballot leader --------
    m = inbox["p2a"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)    # per dst
    a_bal = jnp.max(b_in, axis=0)
    a_has = a_bal > 0
    a_slot = m["slot"][a_src, ridx]                       # absolute
    a_cmd = m["cmd"][a_src, ridx]
    acc_ok = a_has & (a_bal >= ballot)
    demote = acc_ok & (a_bal > ballot)                    # someone else leads
    ballot = jnp.where(acc_ok, a_bal, ballot)
    active = active & ~demote
    p1_acks = jnp.where(demote[:, None], False, p1_acks)
    a_rel = a_slot - base                                 # ring position
    a_inw = (a_rel >= 0) & (a_rel < S)
    oh = acc_ok[:, None] & (sidx[None, :] == a_rel[:, None])
    writable = oh & (log_bal <= a_bal[:, None]) & ~log_commit
    log_bal = jnp.where(writable, a_bal[:, None], log_bal)
    log_cmd = jnp.where(writable, a_cmd[:, None], log_cmd)
    # ack ONLY what we durably stored: a slot outside our window was
    # dropped, and acking it would let the leader commit an entry no
    # majority actually holds (lost acceptance after a leader change)
    out_p2b = {
        "valid": (acc_ok & a_inw)[:, None] & (ridx[None, :] == a_src[:, None]),
        "bal": jnp.broadcast_to(a_bal[:, None], (R, R)),
        "slot": jnp.broadcast_to(a_slot[:, None], (R, R)),
    }

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx)

    # ---------------- P2b: leader tallies acks, commits -----------------
    m = inbox["p2b"]
    okb = m["valid"].T & (m["bal"].T == ballot[:, None]) & \
        (active & own_bal)[:, None]                       # (ldr, src)
    brel = m["slot"].T - base[:, None]                    # (ldr, src) ring
    add = okb[:, :, None] & (sidx[None, None, :] == brel[:, :, None])
    log_acks = log_acks | jnp.transpose(add, (0, 2, 1))   # (ldr, slot, src)
    acks_n = jnp.sum(log_acks, axis=2)                    # (ldr, slot)
    newly = ((active & own_bal)[:, None] & (acks_n >= MAJ)
             & ~log_commit & (log_cmd != NO_CMD) & proposed)
    log_commit = log_commit | newly

    # ---------------- P3: commit notifications --------------------------
    # Zombie fences (see sim/ballot_ring.py apply_p3): a higher-ballot
    # P3 deposes the receiver, and the frontier commit only fires for
    # bal >= my promised ballot — a deposed leader partitioned through
    # later rounds must not commit never-chosen same-stale-ballot
    # entries at fellow laggards via its post-adoption upto.
    m = inbox["p3"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    c_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    c_bal = jnp.max(b_in, axis=0)
    c_has = c_bal > 0
    c_slot = m["slot"][c_src, ridx]                       # absolute
    c_cmd = m["cmd"][c_src, ridx]
    c_upto = m["upto"][c_src, ridx]
    fresh3 = c_has & (c_bal >= ballot)
    promote3 = c_has & (c_bal > ballot)
    ballot = jnp.where(promote3, c_bal, ballot)
    active = active & ~promote3
    p1_acks = jnp.where(promote3[:, None], False, p1_acks)
    abs_ = base[:, None] + sidx[None, :]
    c_rel = c_slot - base
    oh = c_has[:, None] & (sidx[None, :] == c_rel[:, None])
    log_cmd = jnp.where(oh, c_cmd[:, None], log_cmd)
    log_bal = jnp.where(oh, jnp.maximum(log_bal, c_bal[:, None]), log_bal)
    log_commit = log_commit | oh
    # frontier commit: slots < upto accepted at the leader's exact ballot
    ohu = (fresh3[:, None] & (abs_ < c_upto[:, None])
           & (log_bal == c_bal[:, None]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- P3: snapshot catch-up for deep laggards -----------
    # My frontier fell below the sender's window base: the slots I still
    # need were recycled everywhere ahead of me.  Adopt the sender's
    # (kv, execute, base) by reference and keep my own in-window commits.
    src_base = base[c_src]
    adopt = c_has & (execute < src_base)
    adv_a = jnp.where(adopt, src_base - base, 0)
    my_bal = _shift(log_bal, adv_a, 0)
    my_cmd = _shift(log_cmd, adv_a, NO_CMD)
    my_com = _shift(log_commit, adv_a, False)
    s_bal, s_cmd, s_com = log_bal[c_src], log_cmd[c_src], log_commit[c_src]
    a2 = adopt[:, None]
    log_bal = jnp.where(a2, jnp.where(s_com, s_bal, my_bal), log_bal)
    log_cmd = jnp.where(a2, jnp.where(s_com, s_cmd, my_cmd), log_cmd)
    log_commit = jnp.where(a2, s_com | my_com, log_commit)
    proposed = jnp.where(a2, False, proposed)
    log_acks = jnp.where(adopt[:, None, None], False, log_acks)
    kv = jnp.where(a2, kv[c_src], kv)
    execute = jnp.where(adopt, execute[c_src], execute)
    next_slot = jnp.where(adopt, jnp.maximum(next_slot, execute), next_slot)
    base = jnp.where(adopt, src_base, base)
    abs_ = base[:, None] + sidx[None, :]

    # ---------------- leader proposes (new cmd or re-proposal) ----------
    is_leader = active & own_bal
    mask_re = (~log_commit) & (~proposed) & (abs_ < next_slot[:, None])
    first_re = jnp.argmin(jnp.where(mask_re, sidx[None, :], S), axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = (next_slot - base) < S                      # window flow control
    rel_next = jnp.clip(next_slot - base, 0, S - 1)
    prop_rel = jnp.where(has_re, first_re, rel_next).astype(jnp.int32)
    prop_slot = base + prop_rel                           # absolute
    is_new = ~has_re & can_new
    new_cmd = encode_cmd(ballot, prop_slot)
    re_cmd = jnp.take_along_axis(log_cmd, prop_rel[:, None], axis=1)[:, 0]
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    prop_cmd = jnp.where(is_new, new_cmd, re_cmd)
    do = is_leader & (has_re | can_new)
    oh = do[:, None] & (sidx[None, :] == prop_rel[:, None])
    log_bal = jnp.where(oh, ballot[:, None], log_bal)
    log_cmd = jnp.where(oh & ~log_commit, prop_cmd[:, None], log_cmd)
    proposed = proposed | oh
    log_acks = log_acks | (oh[:, :, None] & self_only)
    next_slot = next_slot + (is_new & do)
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
        "slot": jnp.broadcast_to(prop_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None], (R, R)),
    }

    # ---------------- execute committed prefix, apply to KV -------------
    advanced = jnp.zeros((R,), jnp.int32)
    running = jnp.ones((R,), bool)
    for e in range(cfg.exec_window):
        rel = execute + e - base                          # ring position
        inb = rel < S
        idx = jnp.clip(rel, 0, S - 1)
        com = jnp.take_along_axis(log_commit, idx[:, None], axis=1)[:, 0]
        running = running & com & inb
        cmd_e = jnp.take_along_axis(log_cmd, idx[:, None], axis=1)[:, 0]
        key_e = cmd_key(cmd_e, K)
        wr = running & (cmd_e >= 0)
        ohk = wr[:, None] & (jnp.arange(K)[None, :] == key_e[:, None])
        kv = jnp.where(ohk, cmd_e[:, None], kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- P3 out: newly committed + frontier retransmit -----
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :], S), axis=1)
    any_new = jnp.any(newly, axis=1)
    # otherwise cycle retransmits through my in-window committed prefix
    # (laggards behind the window are healed by snapshot adoption)
    span = jnp.maximum(new_execute - base, 1)
    rr = ctx.t % span
    p3_rel = jnp.where(any_new, low_new, rr).astype(jnp.int32)
    p3_rel = jnp.clip(p3_rel, 0, S - 1)
    p3_committed = jnp.take_along_axis(
        log_commit, p3_rel[:, None], axis=1)[:, 0]
    p3_cmd = jnp.take_along_axis(log_cmd, p3_rel[:, None], axis=1)[:, 0]
    p3_do = is_leader & p3_committed
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
        "slot": jnp.broadcast_to((base + p3_rel)[:, None], (R, R)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None], (R, R)),
        "upto": jnp.broadcast_to(new_execute[:, None], (R, R)),
    }

    # ---------------- stuck-frontier retry (lost P2a/P2b) ---------------
    stalled = is_leader & (new_execute == execute) & (next_slot > new_execute)
    stuck = jnp.where(stalled, state["stuck"] + 1, 0)
    retry = stuck >= cfg.retry_timeout
    rel_e = jnp.clip(new_execute - base, 0, S - 1)
    ohr = retry[:, None] & (sidx[None, :] == rel_e[:, None])
    proposed = proposed & ~ohr
    stuck = jnp.where(retry, 0, stuck)

    # ---------------- election timer ------------------------------------
    heard = promote | acc_ok | (c_has & (c_bal >= ballot))
    k_jit = jr.fold_in(ctx.rng, 17)
    jitter = jr.randint(k_jit, (R,), 0, cfg.backoff + 1)
    timer = jnp.where(heard | active,
                      cfg.election_timeout + jitter,
                      state["timer"] - 1)
    fire = ~active & (timer <= 0)
    new_bal = (jnp.max(ballot) // STRIDE + 1) * STRIDE + ridx
    ballot = jnp.where(fire, new_bal, ballot)
    p1_acks = jnp.where(fire[:, None], ridx[None, :] == ridx[:, None], p1_acks)
    timer = jnp.where(fire, cfg.election_timeout + jitter, timer)
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
    }

    # ---------------- slide the ring window (slot recycling) ------------
    # keep the last RETAIN executed slots resident for P3 retransmits;
    # anything older is only reachable via snapshot adoption
    new_base = jnp.maximum(base, new_execute - RETAIN)
    adv = new_base - base
    log_bal = _shift(log_bal, adv, 0)
    log_cmd = _shift(log_cmd, adv, NO_CMD)
    log_commit = _shift(log_commit, adv, False)
    proposed = _shift(proposed, adv, False)
    log_acks = _shift(log_acks, adv, False)

    new_state = dict(
        ballot=ballot, active=active, p1_acks=p1_acks, base=new_base,
        log_bal=log_bal, log_cmd=log_cmd, log_commit=log_commit,
        log_acks=log_acks, proposed=proposed, next_slot=next_slot,
        execute=new_execute, kv=kv, timer=timer, stuck=stuck,
    )
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots = executed prefix at the most advanced replica
    (executed implies committed and agreement-checked)."""
    return {
        "committed_slots": jnp.max(state["execute"]),
        "min_execute": jnp.min(state["execute"]),
        "has_leader": jnp.any(state["active"]).astype(jnp.int32),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Per-step safety oracle (generalizes history.go's checker):
    1. Agreement: all committed commands for a slot are equal — checked
       on the base-aligned common window across replicas.
    2. Stability: a committed (slot, cmd) never changes or un-commits
       while it remains in the window; slots recycled out must have
       been executed (execute >= base always).
    3. Ballot monotonicity per replica.
    4. Executed prefix is committed (within the window)."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    # 1. agreement on the aligned window [max(base), max(base)+S)
    align = jnp.max(base) - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    # 2. stability: old commits still in-window must match; the window
    # may only recycle executed slots (base <= execute)
    adv = base - old["base"]
    o_c = _shift(old["log_commit"], adv, False)
    o_cmd = _shift(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    # 3. ballot monotonicity
    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    # 4. executed prefix committed (ring positions below the frontier)
    abs_ = base[:, None] + sidx[None, :]
    v_exec = jnp.sum((abs_ < new["execute"][:, None]) & ~c)

    return (v_agree + v_stable + v_bal + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="paxos_pg",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
)
