"""Multi-Paxos as a pure TPU transition kernel (lane-major layout).

Reference: paxi paxos/paxos.go — single stable leader, phase-1 ballot
election with log recovery from P1b payloads, per-slot phase-2 acceptance
under a majority quorum, P3 commit broadcast, in-order execution
(HandleRequest/HandleP1a/HandleP1b/HandleP2a/HandleP2b/HandleP3) [driver].

TPU re-design (not a translation):
- **Lane-major batch layout** (see sim/lanes.py): the kernel operates on
  the whole group batch with the group axis LAST — state ``(R, G)`` /
  ``(R, S, G)``, mailbox planes ``(src, dst, G)`` — so the 100k-group
  axis feeds the 8x128 vector lanes and every tile is full.  (The
  earlier vmap-over-groups layout put (5, 64)-shaped trailing dims on
  the lanes: <10% occupancy, slower than one CPU core.)
- Per-replica state is a struct-of-arrays over a fixed **ring** of S
  slots: ring position ``i`` holds absolute slot ``base + i``; the
  window slides forward as the execute frontier advances, retaining the
  last ``S//2`` executed slots for laggard healing (the reference's
  unbounded ``log map[int]*entry`` becomes O(window) — 10M slots run
  in a 64-slot ring).
- All handlers run every step on every replica as fully *masked*
  updates (leader/follower divergence is `where`-selected).
- Ballots are ``round * ballot_stride + replica_idx`` int32s
  (paxos ballot.go packs n<<16|id the same way).
- ``Quorum.ACK`` becomes a **bit-packed int32 ack mask** per (leader,
  slot) with ``lax.population_count`` for ``Majority()`` (quorum.go
  [driver]) — p1_acks (R, G), log_acks (R, S, G); the bool planes the
  group-major kernel kept ((G, R, S, R)) were the worst padding
  offenders on TPU.
- Replica-indexed gathers (pick the argmax-ballot sender's message,
  adopt another replica's log) are unrolled over the tiny R axis as
  masked selects — no XLA gather on the hot path; only the slot-axis
  ring shift uses ``take_along_axis``.
- Messages carry ABSOLUTE slot numbers; receivers mask them against
  their own window (out-of-window = silently ignored, like a TCP
  segment for a closed connection).
- P1b log payloads are passed *by reference*: on winning phase-1 the
  new leader merges the current logs of its ackers, base-aligned via a
  per-(leader, acker) shifted select.  A laggard winner first adopts
  the most advanced acker's (kv, execute, base) — the state-transfer/
  log-compaction analog of the host runtime's P1b snapshot.
- P3 carries (slot, cmd) plus a commit frontier ``upto``: a follower
  commits any in-window slot < upto accepted at the leader's exact
  ballot.  A follower whose frontier fell below the leader's window
  base adopts the leader's (kv, execute, base) wholesale (snapshot
  catch-up) and keeps any of its own still-in-window commits.
- Client load: the leader proposes one new command per step while the
  window has room (closed-loop stream with window flow control);
  commands encode (ballot, slot) so the agreement oracle can detect
  any two-leaders-two-values divergence.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.ring import pick_src as _pick_src
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.ring import shift_row as _shift_row
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.ring import take_replica as _take_replica
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1    # empty log entry
NOOP = -2      # hole filled by a recovering leader


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("bal",),
        "p1b": ("bal",),
        "p2a": ("bal", "slot", "cmd"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto"),
    }


def encode_cmd(bal, slot):
    """Unique-ish command id per (ballot, slot) — lets the agreement
    oracle catch divergent decisions. Doubles as the KV write payload."""
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def cmd_key(cmd, n_keys):
    """Hash the command id onto the KV key space."""
    return fib_key(cmd, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    # ack masks are int32 bitfields; bit 31 is the sign bit — shifts wrap
    # mod 32 in XLA, so replica 32 would silently alias replica 0
    require_packable(R)
    i32 = jnp.int32
    return dict(
        ballot=jnp.zeros((R, G), i32),        # highest ballot seen/promised
        active=jnp.zeros((R, G), bool),       # leader with phase-1 done
        p1_acks=jnp.zeros((R, G), i32),       # [ldr] phase-1 ack bitmask
        base=jnp.zeros((R, G), i32),          # abs slot of ring pos 0
        log_bal=jnp.zeros((R, S, G), i32),    # accepted ballot per slot
        log_cmd=jnp.full((R, S, G), NO_CMD, i32),
        log_commit=jnp.zeros((R, S, G), bool),
        log_acks=jnp.zeros((R, S, G), i32),   # [ldr, slot] P2b ack bitmask
        proposed=jnp.zeros((R, S, G), bool),  # P2a sent under my ballot
        next_slot=jnp.zeros((R, G), i32),     # absolute
        execute=jnp.zeros((R, G), i32),       # absolute frontier
        kv=jnp.zeros((R, K, G), i32),
        # replica 0's timer fires at step 0 => immediate first election
        timer=jnp.broadcast_to(
            (jnp.arange(R, dtype=i32) * cfg.election_timeout)[:, None],
            (R, G)),
        stuck=jnp.zeros((R, G), i32),         # frontier-stall counter
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    src_bit = (jnp.int32(1) << ridx)[:, None, None]   # (src, 1, 1)
    self_bit2 = (jnp.int32(1) << ridx)[:, None]       # (R, 1) for (R, G)
    self_bit3 = (jnp.int32(1) << ridx)[:, None, None]  # (R, 1, 1) for (R,S,G)

    ballot = state["ballot"]          # (R, G)
    active = state["active"]
    p1_acks = state["p1_acks"]
    base = state["base"]
    log_bal = state["log_bal"]        # (R, S, G)
    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    log_acks = state["log_acks"]
    proposed = state["proposed"]
    next_slot = state["next_slot"]
    execute = state["execute"]
    kv = state["kv"]                  # (R, K, G)

    # ---------------- P1a: promise to the highest proposer --------------
    m = inbox["p1a"]                                     # planes (src,dst,G)
    b_in = jnp.where(m["valid"], m["bal"], 0)
    p1a_bal = jnp.max(b_in, axis=0)                      # (dst, G)
    p1a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    promote = p1a_bal > ballot
    ballot = jnp.maximum(ballot, p1a_bal)
    active = active & ~promote
    p1_acks = jnp.where(promote, 0, p1_acks)             # my old round died
    # P1b out (log payload by reference; see module docstring)
    p1b_valid = promote[:, None, :] & (ridx[None, :, None]
                                       == p1a_src[:, None, :])
    out_p1b = {"valid": p1b_valid,
               "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, ballot.shape[-1]))}

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx[:, None])

    # ---------------- P1b: collect phase-1 acks (bitmask) ---------------
    m = inbox["p1b"]
    cond = m["valid"] & (m["bal"] == ballot[None, :, :]) \
        & own_bal[None, :, :]                            # (src, ldr, G)
    p1_acks = p1_acks | jnp.sum(jnp.where(cond, src_bit, 0), axis=0)
    p1_win = own_bal & ~active \
        & (jax.lax.population_count(p1_acks) >= MAJ)
    # amask[ldr, s, g]: did s ack ldr's round (includes self)
    amask = ((p1_acks[:, None, :] >> ridx[None, :, None]) & 1).astype(bool)

    # ---------------- phase-1 win: state transfer from best acker -------
    # A laggard winner's window may sit below its ackers' windows; adopt
    # the most advanced acker's (kv, execute, base) first — by-reference
    # equivalent of the host runtime's P1b (execute, snapshot) transfer.
    exec_am = jnp.where(amask, execute[None, :, :], -1)  # (ldr, s, G)
    f_src = jnp.argmax(exec_am, axis=1).astype(jnp.int32)  # (ldr, G)
    front = jnp.max(exec_am, axis=1)
    el_ad = p1_win & (front > execute)
    kv = jnp.where(el_ad[:, None, :], _take_replica(kv, f_src), kv)
    execute = jnp.where(el_ad, front, execute)
    next_slot = jnp.where(el_ad, jnp.maximum(next_slot, front), next_slot)
    # never adopt a LOWER base: a negative self-shift would drop my own
    # top-of-window entries (possibly committed via P3).  The merge below
    # tolerates ackers whose base is below mine (front-fill only).
    f_base = _take_replica(base, f_src)
    adv_el = jnp.where(el_ad, jnp.maximum(f_base - base, 0), 0)
    base = jnp.where(el_ad, jnp.maximum(f_base, base), base)
    log_bal = _shift(log_bal, adv_el, 0)
    log_cmd = _shift(log_cmd, adv_el, NO_CMD)
    log_commit = _shift(log_commit, adv_el, False)
    proposed = _shift(proposed, adv_el, False)
    log_acks = _shift(log_acks, adv_el, 0)

    # ---------------- phase-1 win: merge ackers' logs (base-aligned) ----
    # leader ring pos j <-> abs base[ldr]+j <-> acker ring pos j+off;
    # unrolled over the R ackers, accumulating the highest-ballot value
    # and any committed value per slot — O(R) passes over (R, S, G).
    best_bal = jnp.full_like(log_bal, -1)
    merged_cmd = jnp.full_like(log_cmd, NO_CMD)
    merged_commit = jnp.zeros_like(log_commit)
    committed_cmd = jnp.full_like(log_cmd, NO_CMD)
    for s in range(R):
        sel_s = amask[:, s, :]                           # (ldr, G)
        adv_s = base - base[s][None, :]                  # (ldr, G)
        lb_s = _shift_row(log_bal[s], adv_s, -1)         # (ldr, S, G)
        lc_s = _shift_row(log_cmd[s], adv_s, NO_CMD)
        lm_s = _shift_row(log_commit[s], adv_s, False)
        lb_s = jnp.where(sel_s[:, None, :], lb_s, -1)
        lm_s = lm_s & sel_s[:, None, :]
        upd = lb_s > best_bal
        best_bal = jnp.where(upd, lb_s, best_bal)
        merged_cmd = jnp.where(upd, lc_s, merged_cmd)
        committed_cmd = jnp.where(lm_s & ~merged_commit, lc_s,
                                  committed_cmd)
        merged_commit = merged_commit | lm_s
    abs_ = base[:, None, :] + sidx[None, :, None]        # (R, S, G)
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, abs_ + 1, 0), axis=1)  # (ldr, G) abs
    new_next = jnp.maximum(next_slot, top)
    in_win = abs_ < new_next[:, None, :]                 # slots to own
    w = p1_win[:, None, :]
    # committed slots adopt the committed value; accepted adopt merged;
    # holes below the frontier become NOOP re-proposals.
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    log_cmd = jnp.where(w & in_win, adopt_cmd, log_cmd)
    log_bal = jnp.where(w & in_win, ballot[:, None, :], log_bal)
    log_commit = jnp.where(w & in_win, merged_commit | log_commit,
                           log_commit)
    proposed = jnp.where(w, in_win & (merged_commit | log_commit), proposed)
    log_acks = jnp.where(w, jnp.where(in_win, self_bit3, 0), log_acks)
    next_slot = jnp.where(p1_win, new_next, next_slot)
    active = active | p1_win

    # ---------------- P2a: accept from the highest-ballot leader --------
    m = inbox["p2a"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)   # (dst, G)
    a_bal = jnp.max(b_in, axis=0)
    a_has = a_bal > 0
    a_slot = _pick_src(m["slot"], a_src)                 # absolute
    a_cmd = _pick_src(m["cmd"], a_src)
    acc_ok = a_has & (a_bal >= ballot)
    demote = acc_ok & (a_bal > ballot)                   # someone else leads
    ballot = jnp.where(acc_ok, a_bal, ballot)
    active = active & ~demote
    p1_acks = jnp.where(demote, 0, p1_acks)
    a_rel = a_slot - base                                # ring position
    a_inw = (a_rel >= 0) & (a_rel < S)
    oh = acc_ok[:, None, :] & (sidx[None, :, None] == a_rel[:, None, :])
    writable = oh & (log_bal <= a_bal[:, None, :]) & ~log_commit
    log_bal = jnp.where(writable, a_bal[:, None, :], log_bal)
    log_cmd = jnp.where(writable, a_cmd[:, None, :], log_cmd)
    # ack ONLY what we durably stored: a slot outside our window was
    # dropped, and acking it would let the leader commit an entry no
    # majority actually holds (lost acceptance after a leader change)
    G = ballot.shape[-1]
    out_p2b = {
        "valid": (acc_ok & a_inw)[:, None, :]
        & (ridx[None, :, None] == a_src[:, None, :]),
        "bal": jnp.broadcast_to(a_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(a_slot[:, None, :], (R, R, G)),
    }

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx[:, None])

    # ---------------- P2b: leader tallies acks, commits -----------------
    m = inbox["p2b"]
    okb = m["valid"] & (m["bal"] == ballot[None, :, :]) \
        & (active & own_bal)[None, :, :]                 # (src, ldr, G)
    brel = m["slot"] - base[None, :, :]                  # (src, ldr, G) ring
    for s in range(R):
        oh_s = okb[s][:, None, :] \
            & (sidx[None, :, None] == brel[s][:, None, :])  # (ldr, S, G)
        log_acks = log_acks | jnp.where(oh_s, jnp.int32(1) << s, 0)
    acks_n = jax.lax.population_count(log_acks)          # (ldr, S, G)
    newly = ((active & own_bal)[:, None, :] & (acks_n >= MAJ)
             & ~log_commit & (log_cmd != NO_CMD) & proposed)
    log_commit = log_commit | newly

    # ---------------- P3: commit notifications --------------------------
    m = inbox["p3"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    c_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    c_bal = jnp.max(b_in, axis=0)
    c_has = c_bal > 0
    c_slot = _pick_src(m["slot"], c_src)                 # absolute
    c_cmd = _pick_src(m["cmd"], c_src)
    c_upto = _pick_src(m["upto"], c_src)
    abs_ = base[:, None, :] + sidx[None, :, None]
    c_rel = c_slot - base
    oh = c_has[:, None, :] & (sidx[None, :, None] == c_rel[:, None, :])
    log_cmd = jnp.where(oh, c_cmd[:, None, :], log_cmd)
    log_bal = jnp.where(oh, jnp.maximum(log_bal, c_bal[:, None, :]),
                        log_bal)
    log_commit = log_commit | oh
    # frontier commit: slots < upto accepted at the leader's exact ballot
    ohu = (c_has[:, None, :] & (abs_ < c_upto[:, None, :])
           & (log_bal == c_bal[:, None, :]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- P3: snapshot catch-up for deep laggards -----------
    # My frontier fell below the sender's window base: the slots I still
    # need were recycled everywhere ahead of me.  Adopt the sender's
    # (kv, execute, base) by reference and keep my own in-window commits.
    src_base = _take_replica(base, c_src)
    adopt = c_has & (execute < src_base)
    adv_a = jnp.where(adopt, src_base - base, 0)
    my_bal = _shift(log_bal, adv_a, 0)
    my_cmd = _shift(log_cmd, adv_a, NO_CMD)
    my_com = _shift(log_commit, adv_a, False)
    s_bal = _take_replica(log_bal, c_src)
    s_cmd = _take_replica(log_cmd, c_src)
    s_com = _take_replica(log_commit, c_src)
    a2 = adopt[:, None, :]
    log_bal = jnp.where(a2, jnp.where(s_com, s_bal, my_bal), log_bal)
    log_cmd = jnp.where(a2, jnp.where(s_com, s_cmd, my_cmd), log_cmd)
    log_commit = jnp.where(a2, s_com | my_com, log_commit)
    proposed = jnp.where(a2, False, proposed)
    log_acks = jnp.where(a2, 0, log_acks)
    kv = jnp.where(adopt[:, None, :], _take_replica(kv, c_src), kv)
    execute = jnp.where(adopt, _take_replica(execute, c_src), execute)
    next_slot = jnp.where(adopt, jnp.maximum(next_slot, execute), next_slot)
    base = jnp.where(adopt, src_base, base)
    abs_ = base[:, None, :] + sidx[None, :, None]

    # ---------------- leader proposes (new cmd or re-proposal) ----------
    is_leader = active & own_bal
    mask_re = (~log_commit) & (~proposed) & (abs_ < next_slot[:, None, :])
    first_re = jnp.argmin(jnp.where(mask_re, sidx[None, :, None], S),
                          axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = (next_slot - base) < S                     # window flow control
    rel_next = jnp.clip(next_slot - base, 0, S - 1)
    prop_rel = jnp.where(has_re, first_re, rel_next).astype(jnp.int32)
    prop_slot = base + prop_rel                          # absolute
    is_new = ~has_re & can_new
    new_cmd = encode_cmd(ballot, prop_slot)
    oh_p = sidx[None, :, None] == prop_rel[:, None, :]   # (R, S, G) one-hot
    re_cmd = jnp.sum(jnp.where(oh_p, log_cmd, 0), axis=1)
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    prop_cmd = jnp.where(is_new, new_cmd, re_cmd)
    do = is_leader & (has_re | can_new)
    oh = do[:, None, :] & oh_p
    log_bal = jnp.where(oh, ballot[:, None, :], log_bal)
    log_cmd = jnp.where(oh & ~log_commit, prop_cmd[:, None, :], log_cmd)
    proposed = proposed | oh
    log_acks = log_acks | jnp.where(oh, self_bit3, 0)
    next_slot = next_slot + (is_new & do)
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(prop_slot[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None, :], (R, R, G)),
    }

    # ---------------- execute committed prefix, apply to KV -------------
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(active)
    kidx = jnp.arange(K, dtype=jnp.int32)
    for e in range(cfg.exec_window):
        rel = execute + e - base                         # ring position
        oh_e = sidx[None, :, None] == rel[:, None, :]    # no hit if rel >= S
        com = jnp.any(oh_e & log_commit, axis=1)
        running = running & com
        cmd_e = jnp.sum(jnp.where(oh_e, log_cmd, 0), axis=1)
        key_e = cmd_key(cmd_e, K)
        wr = running & (cmd_e >= 0)
        ohk = wr[:, None, :] & (kidx[None, :, None] == key_e[:, None, :])
        kv = jnp.where(ohk, cmd_e[:, None, :], kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- P3 out: newly committed + frontier retransmit -----
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :, None], S), axis=1)
    any_new = jnp.any(newly, axis=1)
    # otherwise cycle retransmits through my in-window committed prefix
    # (laggards behind the window are healed by snapshot adoption)
    span = jnp.maximum(new_execute - base, 1)
    rr = ctx.t % span
    p3_rel = jnp.where(any_new, low_new, rr).astype(jnp.int32)
    p3_rel = jnp.clip(p3_rel, 0, S - 1)
    oh_3 = sidx[None, :, None] == p3_rel[:, None, :]
    p3_committed = jnp.any(oh_3 & log_commit, axis=1)
    p3_cmd = jnp.sum(jnp.where(oh_3, log_cmd, 0), axis=1)
    p3_do = is_leader & p3_committed
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to((base + p3_rel)[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None, :], (R, R, G)),
        "upto": jnp.broadcast_to(new_execute[:, None, :], (R, R, G)),
    }

    # ---------------- stuck-frontier retry (lost P2a/P2b) ---------------
    stalled = is_leader & (new_execute == execute) \
        & (next_slot > new_execute)
    stuck = jnp.where(stalled, state["stuck"] + 1, 0)
    retry = stuck >= cfg.retry_timeout
    rel_e = jnp.clip(new_execute - base, 0, S - 1)
    ohr = retry[:, None, :] & (sidx[None, :, None] == rel_e[:, None, :])
    proposed = proposed & ~ohr
    stuck = jnp.where(retry, 0, stuck)

    # ---------------- election timer ------------------------------------
    heard = promote | acc_ok | (c_has & (c_bal >= ballot))
    k_jit = jr.fold_in(ctx.rng, 17)
    jitter = jr.randint(k_jit, ballot.shape, 0, cfg.backoff + 1)
    timer = jnp.where(heard | active,
                      cfg.election_timeout + jitter,
                      state["timer"] - 1)
    fire = ~active & (timer <= 0)
    new_bal = (jnp.max(ballot, axis=0)[None, :] // STRIDE + 1) * STRIDE \
        + ridx[:, None]
    ballot = jnp.where(fire, new_bal, ballot)
    p1_acks = jnp.where(fire, self_bit2, p1_acks)
    timer = jnp.where(fire, cfg.election_timeout + jitter, timer)
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G)),
    }

    # ---------------- slide the ring window (slot recycling) ------------
    # keep the last RETAIN executed slots resident for P3 retransmits;
    # anything older is only reachable via snapshot adoption
    new_base = jnp.maximum(base, new_execute - RETAIN)
    adv = new_base - base
    log_bal = _shift(log_bal, adv, 0)
    log_cmd = _shift(log_cmd, adv, NO_CMD)
    log_commit = _shift(log_commit, adv, False)
    proposed = _shift(proposed, adv, False)
    log_acks = _shift(log_acks, adv, 0)

    new_state = dict(
        ballot=ballot, active=active, p1_acks=p1_acks, base=new_base,
        log_bal=log_bal, log_cmd=log_cmd, log_commit=log_commit,
        log_acks=log_acks, proposed=proposed, next_slot=next_slot,
        execute=new_execute, kv=kv, timer=timer, stuck=stuck,
    )
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots = executed prefix at the most advanced replica
    (executed implies committed and agreement-checked); summed over the
    trailing group axis."""
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=0)),
        "has_leader": jnp.sum(jnp.any(state["active"], axis=0)
                              .astype(jnp.int32)),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Per-step safety oracle (generalizes history.go's checker):
    1. Agreement: all committed commands for a slot are equal — checked
       on the base-aligned common window across replicas.
    2. Stability: a committed (slot, cmd) never changes or un-commits
       while it remains in the window; slots recycled out must have
       been executed (execute >= base always).
    3. Ballot monotonicity per replica.
    4. Executed prefix is committed (within the window)."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    # 1. agreement on the aligned window [max(base), max(base)+S)
    align = jnp.max(base, axis=0)[None, :] - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)    # (S, G)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    # 2. stability: old commits still in-window must match; the window
    # may only recycle executed slots (base <= execute)
    adv = base - old["base"]
    o_c = _shift(old["log_commit"], adv, False)
    o_cmd = _shift(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    # 3. ballot monotonicity
    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    # 4. executed prefix committed (ring positions below the frontier)
    abs_ = base[:, None, :] + sidx[None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, None, :]) & ~c)

    return (v_agree + v_stable + v_bal + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="paxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
