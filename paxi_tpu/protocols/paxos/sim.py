"""Multi-Paxos as a pure TPU transition kernel.

Reference: paxi paxos/paxos.go — single stable leader, phase-1 ballot
election with log recovery from P1b payloads, per-slot phase-2 acceptance
under a majority quorum, P3 commit broadcast, in-order execution
(HandleRequest/HandleP1a/HandleP1b/HandleP2a/HandleP2b/HandleP3) [driver].

TPU re-design (not a translation):
- Per-replica state is a struct-of-arrays over a fixed slot window; all
  handlers run every step on every replica as fully *masked* updates
  (leader/follower divergence is `where`-selected, never branched).
- Ballots are ``round * ballot_stride + replica_idx`` int32s
  (paxos ballot.go packs n<<16|id the same way).
- ``Quorum.ACK`` becomes a boolean ack-matrix OR + popcount
  (p1_acks (R,R); log_acks (R,S,R)) [driver].
- P1b log payloads are passed *by reference*: on winning phase-1 the new
  leader merges the current logs of its ackers (equivalent to each acker
  having sent its P1b later — acceptor entries only grow in ballot, so
  this is safe for the safety oracle).
- P3 carries (slot, cmd) plus a commit frontier ``upto``: a follower may
  commit any slot < upto whose accepted ballot equals the leader's,
  because a leader proposes exactly one command per (ballot, slot).
- Client load: the leader proposes one new command per step (closed-loop
  stream, benchmark.go's generator collapsed into the kernel); commands
  encode (ballot, slot) so the agreement oracle can detect any
  two-leaders-two-values divergence.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1    # empty log entry
NOOP = -2      # hole filled by a recovering leader


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("bal",),
        "p1b": ("bal",),
        "p2a": ("bal", "slot", "cmd"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto"),
    }


def encode_cmd(bal, slot):
    """Unique-ish command id per (ballot, slot) — lets the agreement
    oracle catch divergent decisions. Doubles as the KV write payload."""
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def cmd_key(cmd, n_keys):
    """Hash the command id onto the KV key space."""
    return fib_key(cmd, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array):
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    del rng
    return dict(
        ballot=jnp.zeros((R,), jnp.int32),        # highest ballot seen/promised
        active=jnp.zeros((R,), bool),             # leader with phase-1 done
        p1_acks=jnp.zeros((R, R), bool),          # [ldr, src] phase-1 acks
        log_bal=jnp.zeros((R, S), jnp.int32),     # accepted ballot per slot
        log_cmd=jnp.full((R, S), NO_CMD, jnp.int32),
        log_commit=jnp.zeros((R, S), bool),
        log_acks=jnp.zeros((R, S, R), bool),      # [ldr, slot, src] P2b acks
        proposed=jnp.zeros((R, S), bool),         # P2a sent under my ballot
        next_slot=jnp.zeros((R,), jnp.int32),
        execute=jnp.zeros((R,), jnp.int32),       # first unexecuted slot
        kv=jnp.zeros((R, K), jnp.int32),
        # replica 0's timer fires at step 0 => immediate first election
        timer=jnp.arange(R, dtype=jnp.int32) * cfg.election_timeout,
        stuck=jnp.zeros((R,), jnp.int32),         # frontier-stall counter
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)

    ballot = state["ballot"]
    active = state["active"]
    p1_acks = state["p1_acks"]
    log_bal = state["log_bal"]
    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    log_acks = state["log_acks"]
    proposed = state["proposed"]
    next_slot = state["next_slot"]
    execute = state["execute"]
    kv = state["kv"]

    # ---------------- P1a: promise to the highest proposer --------------
    m = inbox["p1a"]
    b_in = jnp.where(m["valid"], m["bal"], 0)            # (src, dst)
    p1a_bal = jnp.max(b_in, axis=0)                      # per dst
    p1a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    promote = p1a_bal > ballot
    ballot = jnp.maximum(ballot, p1a_bal)
    active = active & ~promote
    p1_acks = jnp.where(promote[:, None], False, p1_acks)  # my old round died
    # P1b out (log payload by reference; see module docstring)
    p1b_valid = promote[:, None] & (ridx[None, :] == p1a_src[:, None])
    out_p1b = {"valid": p1b_valid,
               "bal": jnp.broadcast_to(ballot[:, None], (R, R))}

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx)

    # ---------------- P1b: collect phase-1 acks -------------------------
    m = inbox["p1b"]
    ack = m["valid"].T & (m["bal"].T == ballot[:, None]) & own_bal[:, None]
    p1_acks = p1_acks | ack                               # (ldr, src)
    p1_win = own_bal & ~active & (jnp.sum(p1_acks, axis=1) >= MAJ)

    # ---------------- phase-1 win: merge ackers' logs -------------------
    amask = p1_acks                                       # includes self
    lb = jnp.where(amask[:, :, None], log_bal[None, :, :], -1)  # (ldr,src,S)
    src_best = jnp.argmax(lb, axis=1)                     # (ldr, S)
    best_bal = jnp.max(lb, axis=1)
    merged_cmd = log_cmd[src_best, sidx[None, :]]         # (ldr, S)
    cmask = amask[:, :, None] & log_commit[None, :, :]
    merged_commit = jnp.any(cmask, axis=1)                # (ldr, S)
    csrc = jnp.argmax(cmask, axis=1)
    committed_cmd = log_cmd[csrc, sidx[None, :]]
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, sidx[None, :] + 1, 0), axis=1)  # (ldr,)
    new_next = jnp.maximum(next_slot, top)
    in_win = sidx[None, :] < new_next[:, None]            # slots to own
    w = p1_win[:, None]
    # committed slots adopt the committed value; accepted adopt merged;
    # holes below the frontier become NOOP re-proposals.
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    log_cmd = jnp.where(w & in_win, adopt_cmd, log_cmd)
    log_bal = jnp.where(w & in_win, ballot[:, None], log_bal)
    log_commit = jnp.where(w & in_win, merged_commit | log_commit, log_commit)
    proposed = jnp.where(w, in_win & (merged_commit | log_commit), proposed)
    self_only = (ridx[None, None, :] == ridx[:, None, None])  # (R,1->S,R)
    log_acks = jnp.where(w[:, :, None],
                         in_win[:, :, None] & self_only, log_acks)
    next_slot = jnp.where(p1_win, new_next, next_slot)
    active = active | p1_win

    # ---------------- P2a: accept from the highest-ballot leader --------
    m = inbox["p2a"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)    # per dst
    a_bal = jnp.max(b_in, axis=0)
    a_has = a_bal > 0
    a_slot = m["slot"][a_src, ridx]
    a_cmd = m["cmd"][a_src, ridx]
    acc_ok = a_has & (a_bal >= ballot)
    demote = acc_ok & (a_bal > ballot)                    # someone else leads
    ballot = jnp.where(acc_ok, a_bal, ballot)
    active = active & ~demote
    p1_acks = jnp.where(demote[:, None], False, p1_acks)
    oh = acc_ok[:, None] & (sidx[None, :] == a_slot[:, None])
    writable = oh & (log_bal <= a_bal[:, None]) & ~log_commit
    log_bal = jnp.where(writable, a_bal[:, None], log_bal)
    log_cmd = jnp.where(writable, a_cmd[:, None], log_cmd)
    out_p2b = {
        "valid": acc_ok[:, None] & (ridx[None, :] == a_src[:, None]),
        "bal": jnp.broadcast_to(a_bal[:, None], (R, R)),
        "slot": jnp.broadcast_to(a_slot[:, None], (R, R)),
    }

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx)

    # ---------------- P2b: leader tallies acks, commits -----------------
    m = inbox["p2b"]
    okb = m["valid"].T & (m["bal"].T == ballot[:, None]) & \
        (active & own_bal)[:, None]                       # (ldr, src)
    bslot = m["slot"].T                                   # (ldr, src)
    add = okb[:, :, None] & (sidx[None, None, :] == bslot[:, :, None])
    log_acks = log_acks | jnp.transpose(add, (0, 2, 1))   # (ldr, slot, src)
    acks_n = jnp.sum(log_acks, axis=2)                    # (ldr, slot)
    newly = ((active & own_bal)[:, None] & (acks_n >= MAJ)
             & ~log_commit & (log_cmd != NO_CMD) & proposed)
    log_commit = log_commit | newly

    # ---------------- P3: commit notifications --------------------------
    m = inbox["p3"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    c_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    c_bal = jnp.max(b_in, axis=0)
    c_has = c_bal > 0
    c_slot = m["slot"][c_src, ridx]
    c_cmd = m["cmd"][c_src, ridx]
    c_upto = m["upto"][c_src, ridx]
    oh = c_has[:, None] & (sidx[None, :] == c_slot[:, None])
    log_cmd = jnp.where(oh, c_cmd[:, None], log_cmd)
    log_bal = jnp.where(oh, jnp.maximum(log_bal, c_bal[:, None]), log_bal)
    log_commit = log_commit | oh
    # frontier commit: slots < upto accepted at the leader's exact ballot
    ohu = (c_has[:, None] & (sidx[None, :] < c_upto[:, None])
           & (log_bal == c_bal[:, None]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- leader proposes (new cmd or re-proposal) ----------
    is_leader = active & own_bal
    mask_re = (~log_commit) & (~proposed) & (sidx[None, :] < next_slot[:, None])
    first_re = jnp.argmin(jnp.where(mask_re, sidx[None, :], S), axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = next_slot < S
    prop_slot = jnp.where(has_re, first_re, next_slot).astype(jnp.int32)
    is_new = ~has_re & can_new
    new_cmd = encode_cmd(ballot, prop_slot)
    re_cmd = log_cmd[ridx, jnp.clip(prop_slot, 0, S - 1)]
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    prop_cmd = jnp.where(is_new, new_cmd, re_cmd)
    do = is_leader & (has_re | can_new)
    oh = do[:, None] & (sidx[None, :] == prop_slot[:, None])
    log_bal = jnp.where(oh, ballot[:, None], log_bal)
    log_cmd = jnp.where(oh & ~log_commit, prop_cmd[:, None], log_cmd)
    proposed = proposed | oh
    log_acks = log_acks | (oh[:, :, None] & self_only)
    next_slot = next_slot + (is_new & do)
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
        "slot": jnp.broadcast_to(prop_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None], (R, R)),
    }

    # ---------------- execute committed prefix, apply to KV -------------
    advanced = jnp.zeros((R,), jnp.int32)
    running = jnp.ones((R,), bool)
    for e in range(cfg.exec_window):
        idx = jnp.clip(execute + e, 0, S - 1)
        inb = (execute + e) < S
        com = jnp.take_along_axis(log_commit, idx[:, None], axis=1)[:, 0]
        running = running & com & inb
        cmd_e = jnp.take_along_axis(log_cmd, idx[:, None], axis=1)[:, 0]
        key_e = cmd_key(cmd_e, K)
        wr = running & (cmd_e >= 0)
        ohk = wr[:, None] & (jnp.arange(K)[None, :] == key_e[:, None])
        kv = jnp.where(ohk, cmd_e[:, None], kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- P3 out: newly committed + frontier retransmit -----
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :], S), axis=1)
    any_new = jnp.any(newly, axis=1)
    # otherwise cycle retransmits through my committed prefix (leader-
    # local knowledge only: laggards' holes are all below my frontier,
    # so a round-robin over it eventually re-covers every hole)
    rr = ctx.t % jnp.maximum(new_execute, 1)
    p3_slot = jnp.where(any_new, low_new,
                        jnp.clip(rr, 0, S - 1)).astype(jnp.int32)
    p3_committed = jnp.take_along_axis(
        log_commit, p3_slot[:, None], axis=1)[:, 0]
    p3_cmd = jnp.take_along_axis(log_cmd, p3_slot[:, None], axis=1)[:, 0]
    p3_do = is_leader & p3_committed
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
        "slot": jnp.broadcast_to(p3_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None], (R, R)),
        "upto": jnp.broadcast_to(new_execute[:, None], (R, R)),
    }

    # ---------------- stuck-frontier retry (lost P2a/P2b) ---------------
    stalled = is_leader & (new_execute == execute) & (next_slot > new_execute)
    stuck = jnp.where(stalled, state["stuck"] + 1, 0)
    retry = stuck >= cfg.retry_timeout
    ohr = retry[:, None] & (sidx[None, :] == jnp.clip(new_execute, 0, S - 1)[:, None])
    proposed = proposed & ~ohr
    stuck = jnp.where(retry, 0, stuck)

    # ---------------- election timer ------------------------------------
    heard = promote | acc_ok | (c_has & (c_bal >= ballot))
    k_jit = jr.fold_in(ctx.rng, 17)
    jitter = jr.randint(k_jit, (R,), 0, cfg.backoff + 1)
    timer = jnp.where(heard | active,
                      cfg.election_timeout + jitter,
                      state["timer"] - 1)
    fire = ~active & (timer <= 0)
    new_bal = (jnp.max(ballot) // STRIDE + 1) * STRIDE + ridx
    ballot = jnp.where(fire, new_bal, ballot)
    p1_acks = jnp.where(fire[:, None], ridx[None, :] == ridx[:, None], p1_acks)
    timer = jnp.where(fire, cfg.election_timeout + jitter, timer)
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None], (R, R)),
        "bal": jnp.broadcast_to(ballot[:, None], (R, R)),
    }

    new_state = dict(
        ballot=ballot, active=active, p1_acks=p1_acks, log_bal=log_bal,
        log_cmd=log_cmd, log_commit=log_commit, log_acks=log_acks,
        proposed=proposed, next_slot=next_slot, execute=new_execute,
        kv=kv, timer=timer, stuck=stuck,
    )
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots = executed prefix at the most advanced replica
    (executed implies committed and agreement-checked)."""
    return {
        "committed_slots": jnp.max(state["execute"]),
        "min_execute": jnp.min(state["execute"]),
        "has_leader": jnp.any(state["active"]).astype(jnp.int32),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Per-step safety oracle (generalizes history.go's checker):
    1. Agreement: all committed commands for a slot are equal.
    2. Stability: a committed (slot, cmd) never changes or un-commits.
    3. Ballot monotonicity per replica.
    4. Executed prefix is committed."""
    BIG = jnp.int32(2**30)
    c, cmd = new["log_commit"], new["log_cmd"]
    mx = jnp.max(jnp.where(c, cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(c, cmd, BIG), axis=0)
    n_c = jnp.sum(c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    was = old["log_commit"]
    v_stable = jnp.sum(was & (~c | (cmd != old["log_cmd"])))

    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    prefix_len = jnp.sum(jnp.cumprod(c.astype(jnp.int32), axis=1), axis=1)
    v_exec = jnp.sum(new["execute"] > prefix_len)

    return (v_agree + v_stable + v_bal + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="paxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
)
