"""Multi-Paxos as a pure TPU transition kernel (lane-major layout).

Reference: paxi paxos/paxos.go — single stable leader, phase-1 ballot
election with log recovery from P1b payloads, per-slot phase-2 acceptance
under a majority quorum, P3 commit broadcast, in-order execution
(HandleRequest/HandleP1a/HandleP1b/HandleP2a/HandleP2b/HandleP3) [driver].

TPU re-design (not a translation):
- **Lane-major batch layout** (see sim/lanes.py): the kernel operates on
  the whole group batch with the group axis LAST — state ``(R, G)`` /
  ``(R, S, G)``, mailbox planes ``(src, dst, G)`` — so the 100k-group
  axis feeds the 8x128 vector lanes and every tile is full.  (The
  earlier vmap-over-groups layout put (5, 64)-shaped trailing dims on
  the lanes: <10% occupancy, slower than one CPU core.)
- Per-replica state is a struct-of-arrays over a fixed **ring** of S
  slots with a *fixed cell mapping* (sim/cell.py): absolute slot ``a``
  always lives in cell ``a % S``; the window ``[base, base + S)``
  slides forward as the execute frontier advances, retaining the last
  ``S//2`` executed slots for laggard healing (the reference's
  unbounded ``log map[int]*entry`` becomes O(window) — 10M slots run
  in a 64-slot ring).  Sliding the window is a masked *clear* of
  recycled cells, not a data movement — the per-step
  ``ring.shift_window`` gathers the previous revision paid (~40% of
  lane-major step cost on XLA:CPU) are gone; the frozen pre-rewrite
  kernel survives as ``sim_sw.py`` and this kernel is proven
  bit-canonically equal to it (tests/test_fixed_cell_equiv.py).
- All handlers run every step on every replica as fully *masked*
  updates (leader/follower divergence is `where`-selected).
- Ballots are ``round * ballot_stride + replica_idx`` int32s
  (paxos ballot.go packs n<<16|id the same way).
- ``Quorum.ACK`` becomes a **bit-packed int32 ack mask** per (leader,
  slot) with ``lax.population_count`` for ``Majority()`` (quorum.go
  [driver]) — p1_acks (R, G), log_acks (R, S, G).
- The ballot/ring consensus core (P1a/P1b promise+tally, by-reference
  P1b merge with laggard state transfer, P2a/P2b, P3 commit + snapshot
  catch-up, go-back-N stuck retry, jittered elections, window slide)
  lives in **sim/cell_ring.py**, shared with the sdpaxos and wankeeper
  kernels — this module contributes the client-load model and
  execution.
- Client load: the leader proposes one new command per step while the
  window has room (closed-loop stream with window flow control);
  commands encode (ballot, slot) so the agreement oracle can detect
  any two-leaders-two-values divergence.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim import cell
from paxi_tpu.sim import cell_ring as br
from paxi_tpu.sim import inscan
from paxi_tpu.sim.cell_ring import NO_CMD, NOOP
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx
from paxi_tpu.workload import compile as wlc
from paxi_tpu.workload.spec import CLASSES

# the ballot-ring planes cell_ring.py owns; this kernel adds kv
BR_KEYS = br.KEYS


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("bal",),
        "p1b": ("bal",),
        "p2a": ("bal", "slot", "cmd"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto"),
    }


def encode_cmd(bal, slot):
    """Unique-ish command id per (ballot, slot) — lets the agreement
    oracle catch divergent decisions. Doubles as the KV write payload."""
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def cmd_key(cmd, n_keys):
    """Hash the command id onto the KV key space."""
    return fib_key(cmd, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    # ack masks are int32 bitfields; bit 31 is the sign bit — shifts wrap
    # mod 32 in XLA, so replica 32 would silently alias replica 0
    require_packable(R)
    i32 = jnp.int32
    st = dict(
        ballot=jnp.zeros((R, G), i32),        # highest ballot seen/promised
        active=jnp.zeros((R, G), bool),       # leader with phase-1 done
        p1_acks=jnp.zeros((R, G), i32),       # [ldr] phase-1 ack bitmask
        base=jnp.zeros((R, G), i32),          # window start (absolute)
        log_bal=jnp.zeros((R, S, G), i32),    # accepted ballot per slot
        log_cmd=jnp.full((R, S, G), NO_CMD, i32),
        log_commit=jnp.zeros((R, S, G), bool),
        log_acks=jnp.zeros((R, S, G), i32),   # [ldr, slot] P2b ack bitmask
        proposed=jnp.zeros((R, S, G), bool),  # P2a sent under my ballot
        next_slot=jnp.zeros((R, G), i32),     # absolute
        execute=jnp.zeros((R, G), i32),       # absolute frontier
        kv=jnp.zeros((R, K, G), i32),
        # replica 0's timer fires at step 0 => immediate first election
        timer=jnp.broadcast_to(
            (jnp.arange(R, dtype=i32) * cfg.election_timeout)[:, None],
            (R, G)),
        stuck=jnp.zeros((R, G), i32),         # frontier-stall counter
        # ---- on-device observability (PR-10 ``m_`` zone-accounting
        # template): measurement planes, excluded from the trace
        # witness hash (trace/replay.state_hash), never read by
        # protocol logic (PXM10x).  m_prop_t records each slot's FIRST
        # propose step at its leader; commits bin the propose->commit
        # step delta into the fixed log2 histogram (metrics/lathist);
        # m_inscan_viol accumulates the in-scan linearizability
        # spot-check (sim/inscan).
        m_prop_t=jnp.zeros((R, S, G), i32),
        # pending propose->commit deltas: commits store their delta
        # here (one masked write) and the runner bins them into
        # m_lat_hist every flush_every(S) steps under a lax.cond
        # (runner.flush_measurements) — position-free samples, so the
        # plane is deliberately NOT re-armed with the ring (a recycle
        # clear would drop pending samples); the flush period is
        # shorter than any cell-reuse cycle
        m_commit_dt=jnp.zeros((R, S, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )
    if cfg.workload is not None:
        # GLOBAL group ids: the workload's counter-based draws key on
        # (group, absolute slot), so a sharded mesh can re-derive its
        # slice exactly — parallel/mesh.py offsets this plane by the
        # shard's group base after the in-shard init.  NOT m_-prefixed
        # (it feeds the command key derivation, deliberately).
        st["wl_gid"] = jnp.arange(G, dtype=i32)
        # per-key-class commit-latency planes (hot/warm/cold): binned
        # directly at commit (no pending/deferred flush — the runner's
        # flush path only knows m_commit_dt/m_lat_hist, and workload
        # runs are bench-scale)
        for nm in CLASSES:
            st[f"m_wl_hist_{nm}"] = lathist.empty_hist(G)
            st[f"m_wl_sum_{nm}"] = jnp.zeros((G,), i32)
    return st


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    sidx = jnp.arange(S, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)

    st = {k: state[k] for k in BR_KEYS}
    kv = state["kv"]
    # measurement planes (never passed into cell_ring: the helpers
    # recycle cells on base advances, so m_prop_t is re-armed here by
    # the SAME clear after every base-moving call — cell.advance_clear
    # is the fixed-cell twin of the old re-alignment shift)
    m_prop_t = state["m_prop_t"]
    m_lat_hist = state["m_lat_hist"]
    m_lat_sum = state["m_lat_sum"]

    # ---------------- ballot/ring consensus core (shared) ---------------
    st, out_p1b, promote = br.promise_p1a(st, inbox["p1a"])
    st, p1_win, amask = br.tally_p1b(st, inbox["p1b"], MAJ, STRIDE)
    b0 = st["base"]
    st, ex = br.adopt_best_acker(st, amask, p1_win, {"kv": kv})
    kv = ex["kv"]
    m_prop_t = cell.advance_clear(m_prop_t, b0, st["base"], 0)
    st = br.merge_acker_logs(st, amask, p1_win)
    # a takeover restarts the adopted slots' latency clocks (re-owned
    # re-proposals measure from the takeover, like the wpaxos kernel)
    m_prop_t = jnp.where(p1_win[:, None, :] & st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    st, out_p2b, acc_ok, _ = br.accept_p2a(st, inbox["p2a"])
    st, newly = br.tally_p2b(st, inbox["p2b"], MAJ, STRIDE)
    # in-kernel commit latency: every newly committed (leader, slot)
    # stores its propose->commit step delta in the pending plane; the
    # runner's deferred flush log2-bins it (see init_state)
    dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_commit_dt = jnp.where(newly, dt, state["m_commit_dt"])
    m_lat_sum = m_lat_sum + jnp.sum(jnp.where(newly, dt, 0),
                                    axis=(0, 1), dtype=jnp.int32)
    # per-key-class latency (workload runs): the committed cell's key
    # class derives from (group, absolute slot) — the same counter
    # draw the executor uses for the key id — so commits bin into the
    # hot/warm/cold histograms without carrying anything extra
    wl = cfg.workload
    wl_planes = {}
    if wl is not None:
        gid = state["wl_gid"]                           # (G,) global ids
        clsP = wlc.class_plane(wl, K, gid[None, None, :],
                               cell.cell_abs(st["base"], S))
        for ci, nm in enumerate(CLASSES):
            mask = newly & (clsP == ci)
            wl_planes[f"m_wl_hist_{nm}"] = lathist.hist_update(
                state[f"m_wl_hist_{nm}"], dt, mask)
            wl_planes[f"m_wl_sum_{nm}"] = state[f"m_wl_sum_{nm}"] \
                + jnp.sum(jnp.where(mask, dt, 0), axis=(0, 1),
                          dtype=jnp.int32)
        wl_planes["wl_gid"] = gid
    b0 = st["base"]
    st, ex, c_has, c_bal = br.apply_p3(st, inbox["p3"], {"kv": kv})
    kv = ex["kv"]
    m_prop_t = cell.advance_clear(m_prop_t, b0, st["base"], 0)

    # ---------------- leader proposes (new cmd or re-proposal) ----------
    # the closed-loop client: one fresh command per step, window
    # permitting — this block is what distinguishes this kernel from
    # other cell_ring users
    is_leader = st["active"] & br.own_bal_mask(st, STRIDE)
    has_re, can_new, prop_cell, prop_slot, oh_p, re_cmd = \
        br.repropose_target(st)
    if wl is not None:
        # flash-crowd lowering for the closed proposer loop: NEW
        # commands run the spec's demand gate (1/mult duty cycle
        # outside surge windows); re-proposals always proceed —
        # gating recovery would be a liveness bug, not a workload
        gate = wlc.demand_gate(wl, state["wl_gid"][None, :], ctx.t)
        if gate is not None:
            can_new = can_new & gate
    is_new = ~has_re & can_new
    prop_cmd = jnp.where(is_new, encode_cmd(st["ballot"], prop_slot),
                         re_cmd)
    do = is_leader & (has_re | can_new)
    # latency clock: a slot's FIRST propose starts it (re-proposals and
    # go-back-N retries keep the original start — honest end-to-end
    # commit latency; recycled cells re-arm via the advance clears)
    m_prop_t = jnp.where(do[:, None, :] & oh_p & ~st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    st, out_p2a = br.propose_write(st, do, is_new, prop_cmd, prop_slot,
                                   oh_p)

    # ---------------- execute committed prefix, apply to KV -------------
    execute = st["execute"]
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(st["active"])
    for e in range(cfg.exec_window):
        abs_e = execute + e                              # (R, G) absolute
        inb_e = abs_e < st["base"] + S                   # execute >= base
        oh_e = inb_e[:, None, :] & (sidx[None, :, None]
                                    == jnp.remainder(abs_e, S)[:, None, :])
        com = jnp.any(oh_e & st["log_commit"], axis=1)
        running = running & com
        cmd_e = jnp.sum(jnp.where(oh_e, st["log_cmd"], 0), axis=1)
        if wl is None:
            key_e = cmd_key(cmd_e, K)
            wr = running & (cmd_e >= 0)
        else:
            # workload command plane: key id + read flag derive from
            # (global group id, absolute slot) — identical at every
            # replica, every layout, every shard; reads execute (they
            # advance the frontier) but never write the KV
            gidb = state["wl_gid"][None, :]              # (1, G)
            key_e = wlc.key_plane(wl, K, gidb, abs_e)
            wr = running & (cmd_e >= 0) \
                & ~wlc.read_plane(wl, gidb, abs_e)
        ohk = wr[:, None, :] & (kidx[None, :, None] == key_e[:, None, :])
        kv = jnp.where(ohk, cmd_e[:, None, :], kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- wrap-up: P3 out, retry, election, slide -----------
    out_p3 = br.p3_out(st, newly, new_execute, is_leader, ctx.t)
    st = br.retry_stuck(st, new_execute, is_leader, cfg.retry_timeout)
    heard = promote | acc_ok | (c_has & (c_bal >= st["ballot"]))
    st, out_p1a = br.election_tick(st, heard, ctx.rng, cfg)
    b0 = st["base"]
    st = br.slide_window(st, new_execute, RETAIN)
    m_prop_t = cell.advance_clear(m_prop_t, b0, st["base"], 0)

    # in-scan linearizability spot-check (sim/inscan): an independent
    # oracle beside invariants(), accumulated on device per group
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], st["execute"], state["base"], st["base"],
        cell.cell_abs(state["base"], S), cell.cell_abs(st["base"], S),
        state["log_cmd"], st["log_cmd"],
        state["log_commit"], st["log_commit"],
        kv=kv, lane_major=True)

    new_state = dict(st, kv=kv, m_prop_t=m_prop_t,
                     m_commit_dt=m_commit_dt, m_lat_hist=m_lat_hist,
                     m_lat_sum=m_lat_sum, m_inscan_viol=m_inscan_viol,
                     **wl_planes)
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots = executed prefix at the most advanced replica
    (executed implies committed and agreement-checked); summed over the
    trailing group axis."""
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=0)),
        "has_leader": jnp.sum(jnp.any(state["active"], axis=0)
                              .astype(jnp.int32)),
        # on-device observability scalars (the histogram itself rides
        # in state as m_lat_hist — vectors don't fit the metrics
        # dict); the sample count includes deltas still pending the
        # runner's deferred flush
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": (jnp.sum(state["m_lat_hist"])
                         + jnp.sum((state["m_commit_dt"] > 0)
                                   .astype(jnp.int32))),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
        # per-key-class sample counts (workload runs; the full
        # per-class histograms ride in state — workload.class_split)
        **{f"wl_{nm}_n": jnp.sum(state[f"m_wl_hist_{nm}"])
           for nm in CLASSES if f"m_wl_hist_{nm}" in state},
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Per-step safety oracle (generalizes history.go's checker):
    1. Agreement: all committed commands for a slot are equal — checked
       on the common window across replicas (cells align under the
       fixed mapping, so this is a masked elementwise compare).
    2. Stability: a committed (slot, cmd) never changes or un-commits
       while it remains in the window; slots recycled out must have
       been executed (execute >= base always).
    3. Ballot monotonicity per replica.
    4. Executed prefix is committed (within the window)."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]
    A = cell.cell_abs(base, S)                           # (R, S, G)

    # 1. agreement on the common window [max(base), max(base)+S): cell
    # c refers to the same absolute slot at every replica whose window
    # contains it (all in-window abs values are congruent mod S)
    vis = c & (A >= jnp.max(base, axis=0)[None, None, :])
    mx = jnp.max(jnp.where(vis, cmd, -BIG), axis=0)      # (S, G)
    mn = jnp.min(jnp.where(vis, cmd, BIG), axis=0)
    n_c = jnp.sum(vis, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    # 2. stability: old commits still in-window live in the SAME cell
    # (fixed mapping) and must match; the window may only recycle
    # executed slots (base <= execute)
    o_c = old["log_commit"] \
        & (cell.cell_abs(old["base"], S) >= base[:, None, :])
    v_stable = jnp.sum(o_c & (~c | (cmd != old["log_cmd"])))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    # 3. ballot monotonicity
    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    # 4. executed prefix committed (cells below the frontier)
    v_exec = jnp.sum((A < new["execute"][:, None, :]) & ~c)

    return (v_agree + v_stable + v_bal + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="paxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
