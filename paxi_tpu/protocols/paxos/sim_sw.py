"""FROZEN pre-rewrite reference: the sliding-window (ring-position)
lane-major paxos kernel, kept verbatim from before the fixed-cell
rewrite (PR 15) as the equivalence-proof counterpart.

Ring layout contract (the OLD one): ring position ``i`` holds absolute
slot ``base + i``; every base advance is a ``ring.shift_window`` data
movement.  The live kernel in ``sim.py`` holds absolute slot ``a`` at
cell ``a % S`` forever (sim/cell.py) and must stay BIT-CANONICALLY
equal to this module on pinned fuzz seeds: same PRNG draws, same
outboxes, same counters, and a state that matches after rolling each
ring plane to window order (cell.window_view_np) —
tests/test_fixed_cell_equiv.py enforces it, and ``python -m paxi_tpu
profile --gathers`` diffs the two compiled HLOs' gather counts.  Do
not edit except to mirror a semantic (non-layout) change in sim.py.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim import ballot_ring as br
from paxi_tpu.sim import inscan
from paxi_tpu.sim.ballot_ring import NO_CMD, NOOP
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

# the ballot-ring planes ballot_ring.py owns; this kernel adds kv
BR_KEYS = br.KEYS


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("bal",),
        "p1b": ("bal",),
        "p2a": ("bal", "slot", "cmd"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto"),
    }


def encode_cmd(bal, slot):
    """Unique-ish command id per (ballot, slot) — lets the agreement
    oracle catch divergent decisions. Doubles as the KV write payload."""
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def cmd_key(cmd, n_keys):
    """Hash the command id onto the KV key space."""
    return fib_key(cmd, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    # ack masks are int32 bitfields; bit 31 is the sign bit — shifts wrap
    # mod 32 in XLA, so replica 32 would silently alias replica 0
    require_packable(R)
    i32 = jnp.int32
    return dict(
        ballot=jnp.zeros((R, G), i32),        # highest ballot seen/promised
        active=jnp.zeros((R, G), bool),       # leader with phase-1 done
        p1_acks=jnp.zeros((R, G), i32),       # [ldr] phase-1 ack bitmask
        base=jnp.zeros((R, G), i32),          # abs slot of ring pos 0
        log_bal=jnp.zeros((R, S, G), i32),    # accepted ballot per slot
        log_cmd=jnp.full((R, S, G), NO_CMD, i32),
        log_commit=jnp.zeros((R, S, G), bool),
        log_acks=jnp.zeros((R, S, G), i32),   # [ldr, slot] P2b ack bitmask
        proposed=jnp.zeros((R, S, G), bool),  # P2a sent under my ballot
        next_slot=jnp.zeros((R, G), i32),     # absolute
        execute=jnp.zeros((R, G), i32),       # absolute frontier
        kv=jnp.zeros((R, K, G), i32),
        # replica 0's timer fires at step 0 => immediate first election
        timer=jnp.broadcast_to(
            (jnp.arange(R, dtype=i32) * cfg.election_timeout)[:, None],
            (R, G)),
        stuck=jnp.zeros((R, G), i32),         # frontier-stall counter
        # ---- on-device observability (PR-10 ``m_`` zone-accounting
        # template): measurement planes, excluded from the trace
        # witness hash (trace/replay.state_hash), never read by
        # protocol logic (PXM10x).  m_prop_t records each slot's FIRST
        # propose step at its leader; commits bin the propose->commit
        # step delta into the fixed log2 histogram (metrics/lathist);
        # m_inscan_viol accumulates the in-scan linearizability
        # spot-check (sim/inscan).
        m_prop_t=jnp.zeros((R, S, G), i32),
        # pending propose->commit deltas: commits store their delta
        # here (one masked write) and the runner bins them into
        # m_lat_hist every flush_every(S) steps under a lax.cond
        # (runner.flush_measurements) — position-free samples, so the
        # plane is deliberately NOT shifted with the ring (a shift's
        # fill would drop pending samples); the flush period is
        # shorter than any cell-reuse cycle
        m_commit_dt=jnp.zeros((R, S, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    sidx = jnp.arange(S, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)

    st = {k: state[k] for k in BR_KEYS}
    kv = state["kv"]
    # measurement planes (never passed into ballot_ring: the helpers
    # shift the log planes by base deltas, so m_prop_t is re-aligned
    # here by the SAME delta after every base-moving call)
    m_prop_t = state["m_prop_t"]
    m_lat_hist = state["m_lat_hist"]
    m_lat_sum = state["m_lat_sum"]

    # ---------------- ballot/ring consensus core (shared) ---------------
    st, out_p1b, promote = br.promise_p1a(st, inbox["p1a"])
    st, p1_win, amask = br.tally_p1b(st, inbox["p1b"], MAJ, STRIDE)
    b0 = st["base"]
    st, ex = br.adopt_best_acker(st, amask, p1_win, {"kv": kv})
    kv = ex["kv"]
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)
    st = br.merge_acker_logs(st, amask, p1_win)
    # a takeover restarts the adopted slots' latency clocks (re-owned
    # re-proposals measure from the takeover, like the wpaxos kernel)
    m_prop_t = jnp.where(p1_win[:, None, :] & st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    st, out_p2b, acc_ok, _ = br.accept_p2a(st, inbox["p2a"])
    st, newly = br.tally_p2b(st, inbox["p2b"], MAJ, STRIDE)
    # in-kernel commit latency: every newly committed (leader, slot)
    # stores its propose->commit step delta in the pending plane; the
    # runner's deferred flush log2-bins it (see init_state)
    dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_commit_dt = jnp.where(newly, dt, state["m_commit_dt"])
    m_lat_sum = m_lat_sum + jnp.sum(jnp.where(newly, dt, 0),
                                    axis=(0, 1), dtype=jnp.int32)
    b0 = st["base"]
    st, ex, c_has, c_bal = br.apply_p3(st, inbox["p3"], {"kv": kv})
    kv = ex["kv"]
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)

    # ---------------- leader proposes (new cmd or re-proposal) ----------
    # the closed-loop client: one fresh command per step, window
    # permitting — this block is what distinguishes this kernel from
    # other ballot_ring users
    is_leader = st["active"] & br.own_bal_mask(st, STRIDE)
    has_re, can_new, prop_rel, prop_slot, oh_p, re_cmd = \
        br.repropose_target(st)
    is_new = ~has_re & can_new
    prop_cmd = jnp.where(is_new, encode_cmd(st["ballot"], prop_slot),
                         re_cmd)
    do = is_leader & (has_re | can_new)
    # latency clock: a slot's FIRST propose starts it (re-proposals and
    # go-back-N retries keep the original start — honest end-to-end
    # commit latency; recycled cells re-arm via the shift's 0 fill)
    m_prop_t = jnp.where(do[:, None, :] & oh_p & ~st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    st, out_p2a = br.propose_write(st, do, is_new, prop_cmd, prop_slot,
                                   oh_p)

    # ---------------- execute committed prefix, apply to KV -------------
    execute = st["execute"]
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(st["active"])
    for e in range(cfg.exec_window):
        rel = execute + e - st["base"]                   # ring position
        oh_e = sidx[None, :, None] == rel[:, None, :]    # no hit if rel >= S
        com = jnp.any(oh_e & st["log_commit"], axis=1)
        running = running & com
        cmd_e = jnp.sum(jnp.where(oh_e, st["log_cmd"], 0), axis=1)
        key_e = cmd_key(cmd_e, K)
        wr = running & (cmd_e >= 0)
        ohk = wr[:, None, :] & (kidx[None, :, None] == key_e[:, None, :])
        kv = jnp.where(ohk, cmd_e[:, None, :], kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- wrap-up: P3 out, retry, election, slide -----------
    out_p3 = br.p3_out(st, newly, new_execute, is_leader, ctx.t)
    st = br.retry_stuck(st, new_execute, is_leader, cfg.retry_timeout)
    heard = promote | acc_ok | (c_has & (c_bal >= st["ballot"]))
    st, out_p1a = br.election_tick(st, heard, ctx.rng, cfg)
    b0 = st["base"]
    st = br.slide_window(st, new_execute, RETAIN)
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)

    # in-scan linearizability spot-check (sim/inscan): an independent
    # oracle beside invariants(), accumulated on device per group
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], st["execute"], state["base"], st["base"],
        state["base"][:, None, :] + sidx[None, :, None],
        st["base"][:, None, :] + sidx[None, :, None],
        state["log_cmd"], st["log_cmd"],
        state["log_commit"], st["log_commit"],
        kv=kv, lane_major=True)

    new_state = dict(st, kv=kv, m_prop_t=m_prop_t,
                     m_commit_dt=m_commit_dt, m_lat_hist=m_lat_hist,
                     m_lat_sum=m_lat_sum, m_inscan_viol=m_inscan_viol)
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    """Committed slots = executed prefix at the most advanced replica
    (executed implies committed and agreement-checked); summed over the
    trailing group axis."""
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=0)),
        "has_leader": jnp.sum(jnp.any(state["active"], axis=0)
                              .astype(jnp.int32)),
        # on-device observability scalars (the histogram itself rides
        # in state as m_lat_hist — vectors don't fit the metrics
        # dict); the sample count includes deltas still pending the
        # runner's deferred flush
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": (jnp.sum(state["m_lat_hist"])
                         + jnp.sum((state["m_commit_dt"] > 0)
                                   .astype(jnp.int32))),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Per-step safety oracle (generalizes history.go's checker):
    1. Agreement: all committed commands for a slot are equal — checked
       on the base-aligned common window across replicas.
    2. Stability: a committed (slot, cmd) never changes or un-commits
       while it remains in the window; slots recycled out must have
       been executed (execute >= base always).
    3. Ballot monotonicity per replica.
    4. Executed prefix is committed (within the window)."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    # 1. agreement on the aligned window [max(base), max(base)+S)
    align = jnp.max(base, axis=0)[None, :] - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)    # (S, G)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    # 2. stability: old commits still in-window must match; the window
    # may only recycle executed slots (base <= execute)
    adv = base - old["base"]
    o_c = _shift(old["log_commit"], adv, False)
    o_cmd = _shift(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    # 3. ballot monotonicity
    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    # 4. executed prefix committed (ring positions below the frontier)
    abs_ = base[:, None, :] + sidx[None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, None, :]) & ~c)

    return (v_agree + v_stable + v_bal + v_exec).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="paxos_sw",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
