"""Multi-Paxos replica for the host (deployment) runtime.

Reference: paxi paxos/paxos.go + paxos/replica.go — a single stable
leader; phase-1 (P1a/P1b) ballot election with log recovery from P1b
payloads; per-slot phase-2 (P2a/P2b) under a majority quorum; P3 commit
broadcast; in-order execution against the Database; non-leaders Forward
requests to the ballot leader [driver: HandleP1a/P1b/P2a/P2b, Quorum.ACK].

This is the same protocol the TPU sim kernel (sim.py) runs as masked
array updates; here it is the event-driven form for real deployments.

Batched commit path (HT-Paxos, PAPERS.md): the leader accumulates
client commands in a ``BatchBuffer`` (host/batch.py — size bound
``cfg.batch_size``, time bound ``cfg.batch_wait``; the default flushes
on the next event-loop tick) and ONE phase-2 round decides the whole
batch: a slot holds a *list* of commands, P2a/P3 carry the list, and
execution applies it in order with per-command at-most-once filtering
and per-command reply fan-out.  Batch atomicity rides on slot
atomicity — a P2a either reaches an acceptor with the entire batch or
not at all, so no fault schedule can commit a partial batch.  An empty
command list is the NOOP filler for recovered holes.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paxi_tpu.core.ballot import ballot_id, next_ballot
from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.batch import BatchBuffer
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node
from paxi_tpu.obs import ctx_of


def _wire_cmds(cmds: List[Command]) -> List[list]:
    """Commands as wire-friendly lists (codec round-trips lists of
    [key, value, client_id, command_id] under both json and pickle)."""
    return [[c.key, c.value, c.client_id, c.command_id] for c in cmds]


def _cmds_from_wire(wire) -> List[Command]:
    return [Command(int(k), v, cid, int(cmid)) for k, v, cid, cmid in wire]


def _idents(cmds: List[Command]) -> List[Tuple[str, int]]:
    """A batch's identity: the (client_id, command_id) sequence — what
    decides whether a recovered/committed slot still carries the same
    client commands our pending replies are waiting on."""
    return [(c.client_id, c.command_id) for c in cmds]


@register_message
@dataclass
class P1a:
    ballot: int
    # candidate's execute frontier: ackers ship the KV snapshot only
    # when they are ahead of it, so steady-state elections (equal
    # frontiers) pay no O(DB) wire cost
    execute: int = 0


@register_message
@dataclass
class P1b:
    ballot: int
    id: str
    # slot -> [ballot, [[key, value, client_id, command_id], ...], committed]
    log: Dict[int, list] = field(default_factory=dict)
    # state transfer: the log payload omits slots below the sender's
    # execute frontier (log-compaction analog), so the frontier plus a
    # KV snapshot stands in for the executed prefix — without it a new
    # leader behind an all-executed quorum would NOOP-fill committed,
    # executed slots and diverge
    execute: int = 0
    snap: Dict[int, bytes] = field(default_factory=dict)
    # at-most-once session table riding the snapshot: client_id ->
    # [command_id, value] of its highest executed command, so a frontier
    # jump can never re-execute a command whose slot was compacted away
    ctab: Dict[str, list] = field(default_factory=dict)
    # non-KV replicated planes riding the same transfer
    # (db.aux_snapshot): staged/decided 2PC state and migration
    # windows — a frontier jump past an in-doubt txn's prepare (or a
    # migration begin) must carry the stage, not drop it
    aux: Dict = field(default_factory=dict)


@register_message
@dataclass
class P2a:
    """One phase-2 round for one slot — which now carries a whole
    command batch ([] = NOOP filler)."""

    ballot: int
    slot: int
    cmds: list = field(default_factory=list)


@register_message
@dataclass
class P2b:
    ballot: int
    slot: int
    id: str


@register_message
@dataclass
class P3:
    ballot: int
    slot: int
    cmds: list = field(default_factory=list)


@dataclass
class Entry:
    """Reference: paxos.go entry{ballot, command, commit, request,
    quorum, timestamp} — generalized to a command batch with a parallel
    request list (requests[i] answers cmds[i]; None for commands whose
    client connection lives elsewhere)."""

    ballot: int
    cmds: List[Command] = field(default_factory=list)
    commit: bool = False
    requests: List[Optional[Request]] = field(default_factory=list)
    quorum: Optional[Quorum] = None
    timestamp: float = 0.0

    def live_requests(self) -> List[Request]:
        return [r for r in self.requests if r is not None]


class PaxosReplica(Node):
    # message-class hooks: every wire frame is built and registered
    # through these, so a subclass can swap in extended frames (the
    # switchnet tier's sequencer-stamped classes in
    # protocols/switchpaxos/host.py) without re-implementing the
    # phase logic — Node dispatch is keyed on the exact type
    P1A_CLS = P1a
    P1B_CLS = P1b
    P2A_CLS = P2a
    P2B_CLS = P2b
    P3_CLS = P3

    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.ballot = 0
        self.active = False
        self._leader_ballot = 0          # leader-property memo (ballot)
        self._leader_cache: Optional[ID] = None
        self.log: Dict[int, Entry] = {}
        self.slot = -1          # highest slot used (next proposal = slot+1)
        self.execute = 0        # next slot to execute
        self.p1_quorum = Quorum(cfg.ids)
        self.p1b_logs: Dict[ID, Dict[int, list]] = {}
        # id -> (execute, snap, ctab, aux)
        self.p1b_meta: Dict[ID, tuple] = {}
        self.pending: list = []  # requests queued while electing
        # leader-reads barrier: proposal-frontier slot -> reads waiting
        # for every slot <= it to execute (cfg.leader_reads only)
        self._read_barrier: Dict[int, List[Request]] = {}
        # the leader lease that keeps those reads sound across
        # elections (cfg.lease_s):
        # - ``_lease_until``: serving side — barrier reads answer from
        #   local state only within ``lease_s`` of the START of the
        #   last quorum round (phase-1 win or phase-2 commit); past it
        #   the reads fall back to the log (always-safe path).
        # - ``_fence_until``: takeover side — a fresh leader defers its
        #   first proposals for ``lease_s`` after winning phase-1, so
        #   no write can commit while a deposed leader's lease (whose
        #   last renewal round necessarily STARTED before our promises
        #   arrived) may still be serving reads.
        # Every lease timestamp reads the RESOLVED clock
        # (``self.spans.now()``: fabric clock under replay, monotonic
        # perf_counter live) — a wall-clock read here would make lease
        # expiry depend on host wall time during a virtual-clock
        # replay, breaking byte-identical re-runs (PXR165).
        self._lease_until = 0.0
        self._fence_until = 0.0
        self._p1_start = 0.0
        self._fenced: list = []   # proposals stashed behind the fence
        # at-most-once filter (ADVICE r2 medium): client_id -> (highest
        # executed command_id, its value).  Clients issue command_ids
        # monotonically (host/client.py), so a re-proposal of an
        # already-executed command — e.g. one re-pended across a P1b
        # frontier jump whose true outcome was compacted away, or one
        # both committed under an old ballot and forwarded to the new
        # leader — is recognized and skipped deterministically at every
        # replica instead of mutating the DB twice.
        self.ctab: Dict[str, Tuple[int, bytes]] = {}
        # the batched commit path: leader-side request accumulation.
        # Wall timers never fire under the virtual-clock fabric, so a
        # fabric-driven replica is forced onto tick flushes to keep
        # trace replays deterministic.
        self.batch = BatchBuffer(
            self._flush_batch, max_size=cfg.batch_size,
            max_wait=0.0 if self.socket.fabric is not None
            else cfg.batch_wait,
            metrics=self.metrics, spans=self.spans)
        self.register(Request, self.handle_request)
        self.register(P1a, self.handle_p1a)
        self.register(P1b, self.handle_p1b)
        self.register(P2a, self.handle_p2a)
        self.register(P2b, self.handle_p2b)
        self.register(P3, self.handle_p3)

    # ---- leadership ----------------------------------------------------
    @property
    def leader(self) -> Optional[ID]:
        # memoized per ballot: ID construction parses/validates the
        # "zone.node" string, and this property is on the per-request
        # hot path (is_leader per client command)
        if not self.ballot:
            return None
        if self._leader_ballot != self.ballot:
            self._leader_ballot = self.ballot
            self._leader_cache = ballot_id(self.ballot)
        return self._leader_cache

    def is_leader(self) -> bool:
        return self.active and self.leader == self.id

    # ---- leader lease (cfg.leader_reads soundness) --------------------
    def _lease_enabled(self) -> bool:
        return self.cfg.leader_reads and self.cfg.lease_s > 0

    def _lease_ok(self) -> bool:
        """May barrier reads answer from local state right now?"""
        return not self._lease_enabled() \
            or self.spans.now() < self._lease_until

    def _renew_lease(self, round_start: float) -> None:
        """A quorum round that STARTED at ``round_start`` completed:
        a majority was reachable then, so no rival can have finished
        phase-1 before it — local state is authoritative until
        ``round_start + lease_s``."""
        if self._lease_enabled():
            self._lease_until = max(self._lease_until,
                                    round_start + self.cfg.lease_s)

    def run_phase1(self) -> None:
        """paxos.go P1a(): bump ballot, solicit promises."""
        self._p1_start = self.spans.now()
        self.ballot = next_ballot(self.ballot, self.id)
        self.active = False
        self.p1_quorum = Quorum(self.cfg.ids)
        self.p1_quorum.ack(self.id)
        self.p1b_logs = {self.id: self._log_payload()}
        # own db is local: no transfer needed
        self.p1b_meta = {self.id: (self.execute, {}, {}, {})}
        self.socket.broadcast(self.P1A_CLS(self.ballot, self.execute))

    def _log_payload(self) -> Dict[int, list]:
        return {s: [e.ballot, _wire_cmds(e.cmds), e.commit]
                for s, e in self.log.items() if s >= self.execute}

    # ---- client requests ----------------------------------------------
    def handle_request(self, req: Request) -> None:
        self._maybe_drain_fence()
        if self.is_leader():
            # the batched path: one phase-2 round will cover every
            # request that lands in this buffer before the flush bound
            self.batch.add(req)
        elif self.leader is not None and self.leader != self.id:
            self.forward(self.leader, req)
        else:
            self.pending.append(req)
            # start an election only if one of ours isn't already in
            # flight (reference guards with ballot.ID() != self.ID)
            if self.leader != self.id:
                self.run_phase1()

    def _flush_batch(self, reqs: List[Request]) -> None:
        """BatchBuffer flush: propose ONE slot for the whole batch —
        or, if leadership was lost between add and flush, route the
        requests like any other non-leader arrival.

        With ``cfg.leader_reads`` the batch's reads never enter the
        log: they wait at the current proposal frontier and execute
        against the leader's applied state once every earlier slot has
        executed (read-index semantics; module docstring caveat)."""
        if not self.is_leader():
            self.pending.extend(reqs)
            self._drain_pending()
            return
        if not self.cfg.leader_reads:
            self.propose(reqs)
            return
        writes = [r for r in reqs if r.command.value]
        reads = [r for r in reqs if not r.command.value]
        if writes:
            self.propose(writes)
        if reads:
            if not self._lease_ok():
                # lease expired: a newer leader may have committed
                # writes this snapshot misses — order the reads
                # through the log (the always-safe path)
                self.propose(reads)
                return
            barrier = self.slot
            if self.execute > barrier:
                db_get = self.db.get
                for r in reads:
                    r.reply(Reply(r.command,
                                  value=db_get(r.command.key) or b""))
            else:
                self._read_barrier.setdefault(barrier, []).extend(reads)

    def propose(self, reqs: Optional[List[Request]],
                cmds: Optional[List[Command]] = None,
                at_slot: Optional[int] = None) -> None:
        """paxos.go P2a(): assign a slot to the batch, self-ack,
        broadcast one P2a carrying every command.  Behind the takeover
        fence (see ``_fence_until``) proposals stash and drain when a
        deposed leader's lease can no longer be live."""
        self._maybe_drain_fence()
        if self._lease_enabled() and self.spans.now() < self._fence_until:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None   # no loop (sync caller): fence unenforceable
            if loop is not None:
                self._fenced.append((reqs, cmds, at_slot))
                if len(self._fenced) == 1 and self.socket.fabric is None:
                    # live: a wall timer releases the fence.  Under a
                    # fabric there are no wall timers (the delay below
                    # is in resolved-clock units, not seconds) — the
                    # fence drains on the next protocol activity past
                    # the bound instead, keeping replays byte-identical
                    loop.call_later(self._fence_until - self.spans.now(),
                                    self._drain_fence)
                return
        reqs = list(reqs) if reqs else []
        if cmds is None:
            cmds = [r.command for r in reqs]
        if len(reqs) < len(cmds):
            reqs = reqs + [None] * (len(cmds) - len(reqs))
        if at_slot is None:
            self.slot += 1
            slot = self.slot
        else:
            slot = at_slot
            self.slot = max(self.slot, slot)
        q = Quorum(self.cfg.ids)
        q.ack(self.id)
        self.log[slot] = Entry(self.ballot, cmds, requests=reqs, quorum=q,
                               timestamp=self.spans.now())
        # quorum spans for traced requests: opened per batch member at
        # P2a broadcast, closed as one group on majority (_commit).
        # Write-only span traffic — PXO13x pins that no span value ever
        # flows back into protocol state or decisions.
        for i, r in enumerate(reqs):
            self.spans.open(("q", slot, i), "quorum", ctx_of(r),
                            slot=str(slot))
        self.socket.broadcast(self._make_p2a(slot, cmds))
        if q.majority():  # single-replica cluster
            self._commit(slot)

    def _maybe_drain_fence(self) -> None:
        """Release the fence stash once the resolved clock passes the
        bound — the drain path that needs no wall timer (the only one
        available under a virtual-clock fabric)."""
        if self._fenced and self.spans.now() >= self._fence_until:
            self._drain_fence()

    def _drain_fence(self) -> None:
        """The takeover fence elapsed: release the stashed proposals
        (or, if leadership was lost meanwhile, route their requests
        like any other non-leader arrival)."""
        fenced, self._fenced = self._fenced, []
        if not self.is_leader():
            for reqs, _cmds, _slot in fenced:
                self.pending.extend(r for r in (reqs or [])
                                    if r is not None)
            self._drain_pending()
            return
        for args in fenced:
            self.propose(*args)

    # ---- phase 1 -------------------------------------------------------
    def handle_p1a(self, m: P1a) -> None:
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
            self._repend_inflight()
        ahead = self.execute > m.execute and m.ballot >= self.ballot
        snap = self.db.snapshot() if ahead else {}
        ctab = ({c: [i, v] for c, (i, v) in self.ctab.items()}
                if ahead else {})  # stale candidates discard the P1b anyway
        aux = self.db.aux_snapshot() if ahead else {}
        self.socket.send(ballot_id(m.ballot),
                         self.P1B_CLS(self.ballot, str(self.id), self._log_payload(),
                             self.execute, snap, ctab, aux))

    def _repend_inflight(self) -> None:
        """Losing leadership: unflushed batch, barrier reads and
        uncommitted proposals carrying client requests go back to
        pending for forwarding to the new leader."""
        self._lease_until = 0.0   # known-deposed: stop serving reads now
        if self._fenced:
            # stashed proposals carry the old reign's slot assignments;
            # replaying them after a re-election would overwrite entries
            # committed in between — requeue the requests, drop the slots
            fenced, self._fenced = self._fenced, []
            for reqs, _cmds, _slot in fenced:
                self.pending.extend(r for r in (reqs or []) if r is not None)
        self.batch.drain()   # flush sees not-leader: routes to pending
        if self._read_barrier:
            for reads in self._read_barrier.values():
                self.pending.extend(reads)
            self._read_barrier = {}
        for e in self.log.values():
            if not e.commit and e.requests:
                self.pending.extend(e.live_requests())
                e.requests = []
        self._drain_pending()

    def handle_p1b(self, m: P1b) -> None:
        if m.ballot != self.ballot or self.active:
            if m.ballot > self.ballot:
                self.ballot = m.ballot
                self.active = False
            return
        self.p1_quorum.ack(ID(m.id))
        self.p1b_logs[ID(m.id)] = m.log
        self.p1b_meta[ID(m.id)] = (m.execute, m.snap, m.ctab, m.aux)
        if self._p1_complete():
            self._become_leader()

    def _p1_complete(self) -> bool:
        """Is my phase-1 round won and still mine?  Shared with the
        switchnet subclass, whose election can also complete from the
        register-read arrival (handle_switch_snap)."""
        return self.p1_quorum.majority() \
            and ballot_id(self.ballot) == self.id

    def _become_leader(self) -> None:
        """Merge P1b logs: per slot adopt the highest-ballot batch, keep
        committed values, fill holes with NOOP (empty batch); re-propose
        everything in the window (paxos.go HandleP1b recovery path)."""
        self.active = True
        self._renew_lease(self._p1_start)
        if self._lease_enabled():
            # any prior leader's lease renewal round started before our
            # promises arrived, so it expires no later than this fence
            self._fence_until = self.spans.now() + self.cfg.lease_s
        # state transfer first: an acker ahead of our execute frontier
        # has executed (hence committed) everything below it; adopt its
        # snapshot + frontier so the merge never NOOPs an executed slot
        front, snap, ctab, aux = max(self.p1b_meta.values(),
                                     key=lambda fs: fs[0],
                                     default=(0, {}, {}, {}))
        if front > self.execute:
            # adopt the acker's session table first: re-pended requests
            # whose command already executed in a compacted slot must be
            # filtered by _exec, not applied a second time
            for c, (i, v) in ctab.items():
                if c not in self.ctab or self.ctab[c][0] < int(i):
                    self.ctab[c] = (int(i), v)
            # entries the jump skips: uncommitted ones with requests go
            # back to pending (re-proposed in fresh slots); committed
            # ones were decided — acks for writes, the snapshot value
            # for reads (the closest to what in-order _exec would say)
            snap_n = {int(k): v for k, v in snap.items()}
            for s in range(self.execute, front):
                e = self.log.get(s)
                if e is None or not e.requests:
                    continue
                if e.commit:
                    for cmd, req in zip(e.cmds, e.requests):
                        if req is None:
                            continue
                        v = (snap_n.get(cmd.key, b"")
                             if cmd.is_read() else b"")
                        req.reply(Reply(cmd, value=v))
                else:
                    self.pending.extend(e.live_requests())
                e.requests = []
            self.db.restore(snap)
            # the aux planes travel WITH the frontier jump: staged 2PC
            # ops whose prepare slot was compacted away, and open
            # migration windows with their dirty sets
            self.db.restore_aux(aux)
            self.execute = front
            self.slot = max(self.slot, front - 1)
        merged: Dict[int, Tuple[int, list, bool]] = {}
        top = self.slot
        for log in self.p1b_logs.values():
            for s_raw, (bal, wire, committed) in log.items():
                s = int(s_raw)
                top = max(top, s)
                cur = merged.get(s)
                if committed:
                    merged[s] = (bal, wire, True)
                elif cur is None or (not cur[2] and bal > cur[0]):
                    merged[s] = (bal, wire, False)
        for s in range(self.execute, top + 1):
            bal, wire, committed = merged.get(s, (0, [], False))
            cmds = _cmds_from_wire(wire)
            prev = self.log.get(s)
            reqs = prev.requests if prev else []
            if prev is not None and prev.commit:
                continue
            if prev is not None and prev.live_requests() and \
                    _idents(prev.cmds) != _idents(cmds):
                # retry: the slot was taken by a different batch
                self.pending.extend(prev.live_requests())
                prev.requests = reqs = []
            if committed:
                self.log[s] = Entry(bal, cmds, commit=True, requests=reqs)
            else:
                self.propose(reqs, cmds=cmds, at_slot=s)
        self.slot = max(self.slot, top)
        self._exec()
        self._drain_pending()

    def _drain_pending(self) -> None:
        pending, self.pending = self.pending, []
        for req in pending:
            self.handle_request(req)

    # ---- phase 2 -------------------------------------------------------
    def handle_p2a(self, m: P2a) -> None:
        if m.ballot >= self.ballot:
            if m.ballot > self.ballot:
                self.ballot = m.ballot
                self.active = False
                self._repend_inflight()
            e = self.log.get(m.slot)
            if e is None or (not e.commit and m.ballot >= e.ballot):
                reqs = e.requests if e else []
                self.log[m.slot] = Entry(m.ballot, _cmds_from_wire(m.cmds),
                                         requests=reqs)
            self.slot = max(self.slot, m.slot)
        self.socket.send(ballot_id(m.ballot), self._make_p2b(m.slot))

    def _make_p2a(self, slot: int, cmds):
        """P2a factory — the switchnet subclass rides its frontier
        gossip on this frame (register-eviction input)."""
        return self.P2A_CLS(self.ballot, slot, _wire_cmds(cmds))

    def _make_p2b(self, slot: int):
        """P2b factory — the switchnet subclass rides its frontier
        gossip on this frame (register-eviction input)."""
        return self.P2B_CLS(self.ballot, slot, str(self.id))

    def handle_p2b(self, m: P2b) -> None:
        if m.ballot > self.ballot:  # rejected: someone has a newer ballot
            self.ballot = m.ballot
            self.active = False
            self._repend_inflight()
            return
        e = self.log.get(m.slot)
        if (self.active and e is not None and not e.commit
                and m.ballot == self.ballot == e.ballot):
            e.quorum.ack(ID(m.id))        # [driver: Quorum.ACK]
            if e.quorum.majority():
                self._commit(m.slot)

    def _commit(self, slot: int) -> None:
        e = self.log[slot]
        e.commit = True
        self.spans.close_group(("q", slot))
        self._renew_lease(e.timestamp)   # quorum round started then
        self.socket.broadcast(self.P3_CLS(self.ballot, slot, _wire_cmds(e.cmds)))
        self._exec()

    # ---- commit + execution -------------------------------------------
    def handle_p3(self, m: P3) -> None:
        cmds = _cmds_from_wire(m.cmds)
        e = self.log.get(m.slot)
        reqs = e.requests if e else []
        if e is not None and e.live_requests() and \
                _idents(e.cmds) != _idents(cmds):
            # a different batch committed in our slot: retry the
            # clients' requests elsewhere (reference HandleP3 retry path)
            self.pending.extend(e.live_requests())
            e.requests = reqs = []
        self.log[m.slot] = Entry(m.ballot, cmds, commit=True, requests=reqs)
        self.slot = max(self.slot, m.slot)
        self._exec()
        self._drain_pending()

    def _exec(self) -> None:
        """paxos.go exec(): apply the committed prefix in slot order —
        now batch-at-a-time: every command of a committed slot applies
        in batch order with per-client at-most-once filtering (see
        self.ctab) and its reply fans out to the waiting client."""
        while True:
            e = self.log.get(self.execute)
            if e is None or not e.commit:
                break
            reqs = e.requests
            if not reqs:
                # no client connections waiting on this batch (the
                # common case at followers): one-lock tight loop
                if e.cmds:
                    self.db.apply_batch(e.cmds, self.ctab)
                self.execute += 1
                continue
            for i, cmd in enumerate(e.cmds):
                req = reqs[i] if i < len(reqs) else None
                if cmd.key >= 0:
                    last = (self.ctab.get(cmd.client_id)
                            if cmd.client_id else None)
                    if last is not None and cmd.command_id <= last[0]:
                        # duplicate of an already-executed command:
                        # reply with the recorded outcome, never re-apply
                        value = last[1] if cmd.command_id == last[0] else b""
                    else:
                        self.spans.open(("x", self.execute, i), "exec",
                                        ctx_of(req))
                        value = self.db.execute(cmd)
                        self.spans.close(("x", self.execute, i))
                        if cmd.client_id:
                            self.ctab[cmd.client_id] = (cmd.command_id,
                                                        value)
                    if req is not None:
                        self.spans.open(("w", self.execute, i),
                                        "writeback", ctx_of(req))
                        req.reply(Reply(cmd, value=value))
                        self.spans.close(("w", self.execute, i))
                elif req is not None:
                    req.reply(Reply(cmd, err="noop"))
            e.requests = []
            self.execute += 1
        if self._read_barrier:
            self._answer_barrier_reads()

    def _answer_barrier_reads(self) -> None:
        """Leader reads whose barrier slot has fully executed read the
        applied state now (every write they must observe is in) — if
        the lease still vouches for it; otherwise they go through the
        log like writes."""
        done = [s for s in self._read_barrier if s < self.execute]
        db_get = self.db.get
        for s in done:
            reads = self._read_barrier.pop(s)
            if not self._lease_ok():
                self.propose(reads)
                continue
            for r in reads:
                r.reply(Reply(r.command,
                              value=db_get(r.command.key) or b""))


def new_replica(id: ID, cfg: Config) -> PaxosReplica:
    return PaxosReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  Unlike wankeeper's map this one is a
# wire-level identity: the sim kernel's five mailbox planes are exactly
# the host runtime's five message classes, so a minimized sim witness
# ("the run where THIS P2a vanished") projects onto deterministic
# Socket.drop_next directives with no schedule homomorphism caveats.
# (The host P2a now carries a batch; with the fabric's tick flushes a
# trace-driven workload issues one command per round, so batch fill is
# 1 and the per-slot correspondence holds during replays.)
TRACE_MSG_MAP = {
    "p1a": "P1a", "p1b": "P1b", "p2a": "P2a", "p2b": "P2b", "p3": "P3",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal, no host
# analog.  Serves both `paxos` (sim.py) and `paxos_pg` (sim_pg.py) —
# the two kernels share one state vocabulary.
SIM_STATE_MAP = {
    "p1_acks":    "p1_quorum",  # phase-1 ack bitmask <-> Quorum
    "log_bal":    "log",        # accepted-ballot plane <-> Entry.ballot
    "log_cmd":    "log",        # command plane <-> Entry.cmds
    "log_commit": "log",        # commit plane <-> Entry.commit
    "log_acks":   "log",        # per-slot P2b bitmask <-> Entry.quorum
    "next_slot":  "slot",
    "kv":         "db",         # executed state <-> Database
    "base":       "",   # ring-window base: the host log is an unbounded dict
    "proposed":   "",   # own-ballot P2a mask: implied by Entry existence
    "timer":      "",   # election step-timer: host elections are wall-clock
    "stuck":      "",   # go-back-N retry counter (kernel-only)
    # on-device observability (PR 11) — measurement planes, excluded
    # from the trace witness hash; the host twins are the registry's
    # live latency histograms and the post-hoc linearizability checker
    "m_prop_t":      "",
    "m_commit_dt":   "",   # pending deltas for the deferred flush
    "m_lat_hist":    "",
    "m_lat_sum":     "",
    "m_inscan_viol": "",
}
