"""Multi-Paxos replica for the host (deployment) runtime.

Reference: paxi paxos/paxos.go + paxos/replica.go — a single stable
leader; phase-1 (P1a/P1b) ballot election with log recovery from P1b
payloads; per-slot phase-2 (P2a/P2b) under a majority quorum; P3 commit
broadcast; in-order execution against the Database; non-leaders Forward
requests to the ballot leader [driver: HandleP1a/P1b/P2a/P2b, Quorum.ACK].

This is the same protocol the TPU sim kernel (sim.py) runs as masked
array updates; here it is the event-driven form for real deployments.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from paxi_tpu.core.ballot import ballot_id, next_ballot
from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node

NOOP = Command(key=-1, value=b"\x00noop")


@register_message
@dataclass
class P1a:
    ballot: int
    # candidate's execute frontier: ackers ship the KV snapshot only
    # when they are ahead of it, so steady-state elections (equal
    # frontiers) pay no O(DB) wire cost
    execute: int = 0


@register_message
@dataclass
class P1b:
    ballot: int
    id: str
    # slot -> [ballot, key, value, client_id, command_id, committed]
    log: Dict[int, list] = field(default_factory=dict)
    # state transfer: the log payload omits slots below the sender's
    # execute frontier (log-compaction analog), so the frontier plus a
    # KV snapshot stands in for the executed prefix — without it a new
    # leader behind an all-executed quorum would NOOP-fill committed,
    # executed slots and diverge
    execute: int = 0
    snap: Dict[int, bytes] = field(default_factory=dict)
    # at-most-once session table riding the snapshot: client_id ->
    # [command_id, value] of its highest executed command, so a frontier
    # jump can never re-execute a command whose slot was compacted away
    ctab: Dict[str, list] = field(default_factory=dict)


@register_message
@dataclass
class P2a:
    ballot: int
    slot: int
    key: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class P2b:
    ballot: int
    slot: int
    id: str


@register_message
@dataclass
class P3:
    ballot: int
    slot: int
    key: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@dataclass
class Entry:
    """Reference: paxos.go entry{ballot, command, commit, request,
    quorum, timestamp}."""

    ballot: int
    command: Command
    commit: bool = False
    request: Optional[Request] = None
    quorum: Optional[Quorum] = None
    timestamp: float = 0.0


class PaxosReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.ballot = 0
        self.active = False
        self.log: Dict[int, Entry] = {}
        self.slot = -1          # highest slot used (next proposal = slot+1)
        self.execute = 0        # next slot to execute
        self.p1_quorum = Quorum(cfg.ids)
        self.p1b_logs: Dict[ID, Dict[int, list]] = {}
        self.p1b_meta: Dict[ID, tuple] = {}   # id -> (execute, snap, ctab)
        self.pending: list = []  # requests queued while electing
        # at-most-once filter (ADVICE r2 medium): client_id -> (highest
        # executed command_id, its value).  Clients issue command_ids
        # monotonically (host/client.py), so a re-proposal of an
        # already-executed command — e.g. one re-pended across a P1b
        # frontier jump whose true outcome was compacted away, or one
        # both committed under an old ballot and forwarded to the new
        # leader — is recognized and skipped deterministically at every
        # replica instead of mutating the DB twice.
        self.ctab: Dict[str, Tuple[int, bytes]] = {}
        self.register(Request, self.handle_request)
        self.register(P1a, self.handle_p1a)
        self.register(P1b, self.handle_p1b)
        self.register(P2a, self.handle_p2a)
        self.register(P2b, self.handle_p2b)
        self.register(P3, self.handle_p3)

    # ---- leadership ----------------------------------------------------
    @property
    def leader(self) -> Optional[ID]:
        return ballot_id(self.ballot) if self.ballot else None

    def is_leader(self) -> bool:
        return self.active and self.leader == self.id

    def run_phase1(self) -> None:
        """paxos.go P1a(): bump ballot, solicit promises."""
        self.ballot = next_ballot(self.ballot, self.id)
        self.active = False
        self.p1_quorum = Quorum(self.cfg.ids)
        self.p1_quorum.ack(self.id)
        self.p1b_logs = {self.id: self._log_payload()}
        self.p1b_meta = {self.id: (self.execute, {}, {})}  # own db is local
        self.socket.broadcast(P1a(self.ballot, self.execute))

    def _log_payload(self) -> Dict[int, list]:
        return {s: [e.ballot, e.command.key, e.command.value,
                    e.command.client_id, e.command.command_id, e.commit]
                for s, e in self.log.items() if s >= self.execute}

    # ---- client requests ----------------------------------------------
    def handle_request(self, req: Request) -> None:
        if self.is_leader():
            self.propose(req)
        elif self.leader is not None and self.leader != self.id:
            self.forward(self.leader, req)
        else:
            self.pending.append(req)
            # start an election only if one of ours isn't already in
            # flight (reference guards with ballot.ID() != self.ID)
            if self.leader != self.id:
                self.run_phase1()

    def propose(self, req: Optional[Request],
                command: Optional[Command] = None,
                at_slot: Optional[int] = None) -> None:
        """paxos.go P2a(): assign a slot, self-ack, broadcast P2a."""
        cmd = command if command is not None else req.command
        if at_slot is None:
            self.slot += 1
            slot = self.slot
        else:
            slot = at_slot
            self.slot = max(self.slot, slot)
        q = Quorum(self.cfg.ids)
        q.ack(self.id)
        self.log[slot] = Entry(self.ballot, cmd, request=req, quorum=q,
                               timestamp=time.time())
        self.socket.broadcast(P2a(self.ballot, slot, cmd.key, cmd.value,
                                  cmd.client_id, cmd.command_id))
        if q.majority():  # single-replica cluster
            self._commit(slot)

    # ---- phase 1 -------------------------------------------------------
    def handle_p1a(self, m: P1a) -> None:
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
            self._repend_inflight()
        ahead = self.execute > m.execute and m.ballot >= self.ballot
        snap = self.db.snapshot() if ahead else {}
        ctab = ({c: [i, v] for c, (i, v) in self.ctab.items()}
                if ahead else {})  # stale candidates discard the P1b anyway
        self.socket.send(ballot_id(m.ballot),
                         P1b(self.ballot, str(self.id), self._log_payload(),
                             self.execute, snap, ctab))

    def _repend_inflight(self) -> None:
        """Losing leadership: uncommitted proposals carrying client
        requests go back to pending for forwarding to the new leader."""
        for e in self.log.values():
            if not e.commit and e.request is not None:
                self.pending.append(e.request)
                e.request = None
        self._drain_pending()

    def handle_p1b(self, m: P1b) -> None:
        if m.ballot != self.ballot or self.active:
            if m.ballot > self.ballot:
                self.ballot = m.ballot
                self.active = False
            return
        self.p1_quorum.ack(ID(m.id))
        self.p1b_logs[ID(m.id)] = m.log
        self.p1b_meta[ID(m.id)] = (m.execute, m.snap, m.ctab)
        if self.p1_quorum.majority() and ballot_id(self.ballot) == self.id:
            self._become_leader()

    def _become_leader(self) -> None:
        """Merge P1b logs: per slot adopt the highest-ballot command, keep
        committed values, fill holes with NOOP; re-propose everything in
        the window (paxos.go HandleP1b recovery path)."""
        self.active = True
        # state transfer first: an acker ahead of our execute frontier
        # has executed (hence committed) everything below it; adopt its
        # snapshot + frontier so the merge never NOOPs an executed slot
        front, snap, ctab = max(self.p1b_meta.values(),
                                key=lambda fs: fs[0], default=(0, {}, {}))
        if front > self.execute:
            # adopt the acker's session table first: re-pended requests
            # whose command already executed in a compacted slot must be
            # filtered by _exec, not applied a second time
            for c, (i, v) in ctab.items():
                if c not in self.ctab or self.ctab[c][0] < int(i):
                    self.ctab[c] = (int(i), v)
            # entries the jump skips: uncommitted ones with requests go
            # back to pending (re-proposed in fresh slots); committed
            # ones were decided — acks for writes, the snapshot value
            # for reads (the closest to what in-order _exec would say)
            snap_n = {int(k): v for k, v in snap.items()}
            for s in range(self.execute, front):
                e = self.log.get(s)
                if e is None or e.request is None:
                    continue
                if e.commit:
                    v = (snap_n.get(e.command.key, b"")
                         if e.command.is_read() else b"")
                    e.request.reply(Reply(e.command, value=v))
                else:
                    self.pending.append(e.request)
                e.request = None
            self.db.restore(snap)
            self.execute = front
            self.slot = max(self.slot, front - 1)
        merged: Dict[int, Tuple[int, Command, bool]] = {}
        top = self.slot
        for log in self.p1b_logs.values():
            for s_raw, (bal, key, value, cid, cmid, committed) in log.items():
                s = int(s_raw)
                top = max(top, s)
                cmd = Command(int(key), value, cid, int(cmid))
                cur = merged.get(s)
                if committed:
                    merged[s] = (bal, cmd, True)
                elif cur is None or (not cur[2] and bal > cur[0]):
                    merged[s] = (bal, cmd, False)
        for s in range(self.execute, top + 1):
            bal, cmd, committed = merged.get(s, (0, NOOP, False))
            prev = self.log.get(s)
            req = prev.request if prev else None
            if prev is not None and prev.commit:
                continue
            if req is not None and (
                    (prev.command.client_id, prev.command.command_id)
                    != (cmd.client_id, cmd.command_id)):
                self.pending.append(req)   # retry: slot taken by another cmd
                prev.request = req = None
            if committed:
                self.log[s] = Entry(bal, cmd, commit=True, request=req)
            else:
                self.propose(req, command=cmd, at_slot=s)
        self.slot = max(self.slot, top)
        self._exec()
        self._drain_pending()

    def _drain_pending(self) -> None:
        pending, self.pending = self.pending, []
        for req in pending:
            self.handle_request(req)

    # ---- phase 2 -------------------------------------------------------
    def handle_p2a(self, m: P2a) -> None:
        if m.ballot >= self.ballot:
            if m.ballot > self.ballot:
                self.ballot = m.ballot
                self.active = False
                self._repend_inflight()
            e = self.log.get(m.slot)
            if e is None or (not e.commit and m.ballot >= e.ballot):
                req = e.request if e else None
                self.log[m.slot] = Entry(
                    m.ballot, Command(m.key, m.value, m.client_id,
                                      m.command_id), request=req)
            self.slot = max(self.slot, m.slot)
        self.socket.send(ballot_id(m.ballot),
                         P2b(self.ballot, m.slot, str(self.id)))

    def handle_p2b(self, m: P2b) -> None:
        if m.ballot > self.ballot:  # rejected: someone has a newer ballot
            self.ballot = m.ballot
            self.active = False
            self._repend_inflight()
            return
        e = self.log.get(m.slot)
        if (self.active and e is not None and not e.commit
                and m.ballot == self.ballot == e.ballot):
            e.quorum.ack(ID(m.id))        # [driver: Quorum.ACK]
            if e.quorum.majority():
                self._commit(m.slot)

    def _commit(self, slot: int) -> None:
        e = self.log[slot]
        e.commit = True
        c = e.command
        self.socket.broadcast(P3(self.ballot, slot, c.key, c.value,
                                 c.client_id, c.command_id))
        self._exec()

    # ---- commit + execution -------------------------------------------
    def handle_p3(self, m: P3) -> None:
        cmd = Command(m.key, m.value, m.client_id, m.command_id)
        e = self.log.get(m.slot)
        req = e.request if e else None
        if req is not None and (
                (e.command.client_id, e.command.command_id)
                != (cmd.client_id, cmd.command_id)):
            # a different command committed in our slot: retry the
            # client's request elsewhere (reference HandleP3 retry path)
            req = None
            self.pending.append(e.request)
            e.request = None
        self.log[m.slot] = Entry(m.ballot, cmd, commit=True, request=req)
        self.slot = max(self.slot, m.slot)
        self._exec()
        self._drain_pending()

    def _exec(self) -> None:
        """paxos.go exec(): apply the committed prefix in slot order,
        with per-client at-most-once filtering (see self.ctab)."""
        while True:
            e = self.log.get(self.execute)
            if e is None or not e.commit:
                break
            if e.command.key >= 0:  # skip NOOP
                cmd = e.command
                last = self.ctab.get(cmd.client_id) if cmd.client_id else None
                if last is not None and cmd.command_id <= last[0]:
                    # duplicate of an already-executed command: reply
                    # with the recorded outcome, never re-apply
                    value = last[1] if cmd.command_id == last[0] else b""
                else:
                    value = self.db.execute(cmd)
                    if cmd.client_id:
                        self.ctab[cmd.client_id] = (cmd.command_id, value)
                if e.request is not None:
                    e.request.reply(Reply(e.command, value=value))
                    e.request = None
            elif e.request is not None:
                e.request.reply(Reply(e.command, err="noop"))
                e.request = None
            self.execute += 1


def new_replica(id: ID, cfg: Config) -> PaxosReplica:
    return PaxosReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  Unlike wankeeper's map this one is a
# wire-level identity: the sim kernel's five mailbox planes are exactly
# the host runtime's five message classes, so a minimized sim witness
# ("the run where THIS P2a vanished") projects onto deterministic
# Socket.drop_next directives with no schedule homomorphism caveats.
TRACE_MSG_MAP = {
    "p1a": "P1a", "p1b": "P1b", "p2a": "P2a", "p2b": "P2b", "p3": "P3",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal, no host
# analog.  Serves both `paxos` (sim.py) and `paxos_pg` (sim_pg.py) —
# the two kernels share one state vocabulary.
SIM_STATE_MAP = {
    "p1_acks":    "p1_quorum",  # phase-1 ack bitmask <-> Quorum
    "log_bal":    "log",        # accepted-ballot plane <-> Entry.ballot
    "log_cmd":    "log",        # command plane <-> Entry.command
    "log_commit": "log",        # commit plane <-> Entry.commit
    "log_acks":   "log",        # per-slot P2b bitmask <-> Entry.quorum
    "next_slot":  "slot",
    "kv":         "db",         # executed state <-> Database
    "base":       "",   # ring-window base: the host log is an unbounded dict
    "proposed":   "",   # own-ballot P2a mask: implied by Entry existence
    "timer":      "",   # election step-timer: host elections are wall-clock
    "stuck":      "",   # go-back-N retry counter (kernel-only)
}
