from paxi_tpu.ops.hashing import fib_key  # noqa: F401
