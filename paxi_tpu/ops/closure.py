"""Boolean transitive closure by repeated matrix squaring.

Used by the EPaxos execution engine (protocols/epaxos/sim.py): the
committed dependency graph's reachability relation is ``closure(A)``,
SCCs are ``reach & reach^T`` — Tarjan (epaxos exec.go) re-expressed as
batched boolean matmuls that map straight onto the MXU.

Two paths:
- **XLA** (default off-TPU): ``log2(N)`` batched matmuls; XLA handles
  batching/fusion, but each squaring round-trips the matrix through HBM.
- **Pallas** (TPU, or ``PAXI_TPU_PALLAS=1`` with interpret fallback):
  one kernel instance per batch element keeps the (padded-to-128)
  matrix resident in VMEM across ALL squarings — one HBM read and one
  write total.  Zero-padding is closure-neutral (no spurious edges).

Matrices here are small (N = replicas x instance-window, typically
64-256) — the batch axis (groups x replicas) carries the parallelism.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def _n_iter(n: int) -> int:
    return max(1, (max(n, 2) - 1).bit_length())


def closure_xla(adj: jax.Array) -> jax.Array:
    """Repeated squaring in plain XLA; adj: bool[..., N, N]."""
    n = adj.shape[-1]
    reach = adj
    for _ in range(_n_iter(n)):
        sq = jnp.matmul(reach.astype(jnp.float32),
                        reach.astype(jnp.float32)) > 0
        reach = reach | sq
    return reach


def _closure_kernel(n_iter: int, a_ref, out_ref):
    r = a_ref[0].astype(jnp.float32)
    for _ in range(n_iter):
        sq = jax.lax.dot(r, r, preferred_element_type=jnp.float32)
        r = jnp.where(r + sq > 0, 1.0, 0.0)
    out_ref[0] = r > 0


def closure_pallas(adj: jax.Array, interpret: bool = False) -> jax.Array:
    """VMEM-resident closure; adj: bool[B, N, N] (one block per batch)."""
    from jax.experimental import pallas as pl

    b, n, _ = adj.shape
    pad = (-n) % 128
    if pad:
        adj = jnp.pad(adj, ((0, 0), (0, pad), (0, pad)))
    np_ = n + pad
    out = pl.pallas_call(
        functools.partial(_closure_kernel, _n_iter(n)),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, np_, np_), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, np_, np_), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, np_, np_), jnp.bool_),
        interpret=interpret,
    )(adj)
    return out[:, :n, :n]


def transitive_closure(adj: jax.Array) -> jax.Array:
    """Reachability closure of bool[..., N, N] (batched).

    Picks the Pallas VMEM-resident path on TPU (or when
    ``PAXI_TPU_PALLAS`` is set — interpreted off-TPU, for testing);
    plain XLA squaring otherwise.
    """
    mode = os.environ.get("PAXI_TPU_PALLAS", "")
    on_tpu = jax.default_backend() == "tpu"
    if mode == "0" or (not on_tpu and not mode):
        return closure_xla(adj)
    lead = adj.shape[:-2]
    n = adj.shape[-1]
    flat = adj.reshape((-1, n, n))
    out = closure_pallas(flat, interpret=not on_tpu)
    return out.reshape(lead + (n, n))
