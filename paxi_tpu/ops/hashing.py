"""Shared in-kernel integer hashing ops.

The sim kernels hash command/op identifiers onto the KV key space with a
Fibonacci (golden-ratio) multiply — one definition here so all protocol
kernels stay in sync (int32 wrap-around is intended; ``jnp.abs`` of
INT32_MIN wraps back to INT32_MIN but INT32_MIN % n is still a valid
index after ``jnp.abs`` on two's-complement — kept as-is for speed)."""

from __future__ import annotations

import jax.numpy as jnp

GOLDEN = jnp.int32(-1640531527)  # 2654435769 as int32 (2^32 / phi)


def fib_key(x, n_keys: int):
    """Hash int32 ``x`` onto ``[0, n_keys)``."""
    return jnp.abs(x * GOLDEN) % n_keys
