"""Pallas lane-major message-exchange kernels (staged TPU fast path).

The dense exchange (`sim/mailbox.py`, shared by `sim/lanes.py`) builds
the per-step wheel rotate + masked insert out of ~10 XLA ops per
message-type field; on TPU every one of them round-trips the (delay,
src, dst, G) planes through HBM.  This module fuses each half into one
Pallas kernel over lane-major planes — the layout the 8x128 vector
unit tiles natively (see sim/lanes.py) — so a step's exchange touches
each plane once:

- ``wheel_deliver`` / ``wheel_insert``: drop-in replacements for the
  ``sim.mailbox`` pair with identical semantics (same collision rule:
  a new message overwrites an undelivered one in the same wheel cell).
  All fields of a message type move through one kernel invocation as a
  stacked int32 block, gridded over the group (lane) axis.  On
  non-TPU backends the kernels run in interpret mode, which is what
  the CPU correctness test exercises — semantics are pinned to the
  dense exchange bit-for-bit before the TPU ever sees the kernel.
- ``make_remote_lane_shift``: the staged inter-chip half
  (``pltpu.make_async_remote_copy``, SNIPPETS.md [1][2]): rotate a
  lane-major shard to the right mesh neighbor over ICI — the
  group-migration / zone-exchange primitive the cross-device protocols
  (wpaxos zones <-> mesh axis) need.  Real-RDMA only: it traces on a
  TPU mesh and raises elsewhere, so the moment the tunnel heals we run
  the layout this was designed for instead of re-discovering it.

Select at the bench level with ``--backend pallas`` (bench.py); the
runner threads it through ``make_run(..., exchange="pallas")`` for
lane-major kernels.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paxi_tpu.sim import mailbox as mb
from paxi_tpu.sim.lanes import (empty_wheel, fault_state_init,  # noqa: F401
                                fault_state_refresh)
from paxi_tpu.sim.types import FuzzConfig, Mailboxes

MailSpec = Dict[str, Tuple[str, ...]]


def _interpret() -> bool:
    """Interpret everywhere but real TPU — the CPU-pinned semantics are
    the contract; the compiled path is the same kernel body."""
    return jax.default_backend() != "tpu"


def _block_g(g: int) -> int:
    """Grid the lane (group) axis: the largest divisor of ``g`` that
    fits a 128-lane tile, so an off-multiple batch (e.g. the 100k
    north-star shape, 100000 % 128 == 32) still grids into
    VMEM-sized blocks instead of one whole-batch block."""
    for b in range(min(g, 128), 0, -1):
        if g % b == 0:
            return b
    return g


# --------------------------------------------------------------------------
# fused deliver: pop slot 0, rotate the wheel forward
# --------------------------------------------------------------------------

def _deliver_kernel(wheel_ref, inbox_ref, rolled_ref):
    d = wheel_ref.shape[0]
    inbox_ref[...] = wheel_ref[0]
    if d > 1:
        rolled_ref[:d - 1] = wheel_ref[1:]
    rolled_ref[d - 1] = jnp.zeros_like(wheel_ref[0])


def _stack(box, fields):
    """Stack a message type's {valid, *fields} planes into one int32
    block (valid first) so the whole type moves through one kernel."""
    planes = [box["valid"].astype(jnp.int32)]
    planes += [box[f] for f in fields]
    return jnp.stack(planes, axis=-4)   # (..., F, src, dst, G)


def _unstack(stacked, fields):
    out = {"valid": stacked[..., 0, :, :, :] != 0}
    for i, f in enumerate(fields):
        out[f] = stacked[..., i + 1, :, :, :]
    return out


def wheel_deliver(wheel: Mailboxes) -> Tuple[Mailboxes, Mailboxes]:
    """Pop slot 0 as this step's inbox; rotate the wheel forward.
    Pallas-fused per message type; semantics = mailbox.wheel_deliver."""
    inbox, rolled = {}, {}
    for name, box in wheel.items():
        fields = tuple(k for k in box if k != "valid")
        st = _stack(box, fields)                    # (d, F, R, R, G)
        d, F, R, _, G = st.shape
        gb = _block_g(G)
        out = pl.pallas_call(
            _deliver_kernel,
            grid=(G // gb,),
            in_specs=[pl.BlockSpec((d, F, R, R, gb),
                                   lambda i: (0, 0, 0, 0, i))],
            out_shape=(jax.ShapeDtypeStruct((F, R, R, G), jnp.int32),
                       jax.ShapeDtypeStruct((d, F, R, R, G), jnp.int32)),
            out_specs=(pl.BlockSpec((F, R, R, gb),
                                    lambda i: (0, 0, 0, i)),
                       pl.BlockSpec((d, F, R, R, gb),
                                    lambda i: (0, 0, 0, 0, i))),
            interpret=_interpret(),
        )(st)
        inbox[name] = _unstack(out[0], fields)
        rolled[name] = _unstack(out[1], fields)
    return inbox, rolled


# --------------------------------------------------------------------------
# fused insert: masked scatter of the outbox into the wheel
# --------------------------------------------------------------------------

def _insert_kernel(wheel_ref, out_ref, eff_ref, delay_ref, dup_ref,
                   new_ref):
    d, F = wheel_ref.shape[0], wheel_ref.shape[1]
    eff = eff_ref[...] != 0
    delay = delay_ref[...]
    dup = dup_ref[...] != 0
    dup_delay = jnp.minimum(delay + 1, d)
    for slot in range(d):
        put = eff & ((delay == slot + 1) | (dup & (dup_delay == slot + 1)))
        new_ref[slot, 0] = ((wheel_ref[slot, 0] != 0) | put).astype(
            jnp.int32)
        for f in range(1, F):
            new_ref[slot, f] = jnp.where(put, out_ref[f],
                                         wheel_ref[slot, f])


def wheel_insert(wheel: Mailboxes, outbox: Mailboxes, fs,
                 fuzz: FuzzConfig, faults: Mailboxes) -> Mailboxes:
    """Push this step's outbox into the wheel under the fault schedule.
    Pallas-fused per message type; semantics = mailbox.wheel_insert
    (one definition of the delivery-validity predicate — live_mask —
    keeps the replay guarantee shared with the dense exchange)."""
    d = fuzz.wheel
    new_wheel = {}
    for name in sorted(outbox.keys()):
        box, wbox = outbox[name], wheel[name]
        fields = tuple(k for k in wbox if k != "valid")
        n = box["valid"].shape[0]
        f = faults[name]
        eff = (box["valid"] & mb.live_mask(fs, box["valid"].ndim, n)
               & ~f["drop"])
        st = _stack(wbox, fields)                   # (d, F, R, R, G)
        ob = _stack(box, fields)                    # (F, R, R, G)
        _, F, R, _, G = st.shape
        gb = _block_g(G)
        spec3 = pl.BlockSpec((R, R, gb), lambda i: (0, 0, i))
        out = pl.pallas_call(
            _insert_kernel,
            grid=(G // gb,),
            in_specs=[pl.BlockSpec((d, F, R, R, gb),
                                   lambda i: (0, 0, 0, 0, i)),
                      pl.BlockSpec((F, R, R, gb),
                                   lambda i: (0, 0, 0, i)),
                      spec3, spec3, spec3],
            out_shape=jax.ShapeDtypeStruct((d, F, R, R, G), jnp.int32),
            out_specs=pl.BlockSpec((d, F, R, R, gb),
                                   lambda i: (0, 0, 0, 0, i)),
            interpret=_interpret(),
        )(st, ob, eff.astype(jnp.int32), f["delay"],
          f["dup"].astype(jnp.int32))
        new_wheel[name] = _unstack(out, fields)
    return new_wheel


# --------------------------------------------------------------------------
# staged: inter-chip lane shift over ICI (real RDMA, TPU only)
# --------------------------------------------------------------------------

def make_remote_lane_shift(mesh, axis: str = "i"):
    """Build ``shift(x)``: rotate each device's lane-major shard
    ``(..., G_local)`` to its right mesh neighbor with one async remote
    copy (``pltpu.make_async_remote_copy`` — SNIPPETS.md [1][2]).  The
    staged group-migration primitive: when groups (or WPaxos zones) map
    onto the mesh axis, a leadership steal is this shift instead of a
    host round-trip.

    Traces only on a TPU mesh — the DMA semaphores and ICI routing have
    no CPU analog (the CPU-testable exchange above is interpret-mode;
    this one is the hardware half)."""
    if jax.default_backend() != "tpu":   # pragma: no cover - TPU only
        raise NotImplementedError(
            "remote lane shift needs real ICI RDMA; on CPU use "
            "jnp.roll over the gathered axis (the sim's mesh psum "
            "path) — this kernel is staged for the TPU backend")

    from jax.experimental.pallas import tpu as pltpu  # pragma: no cover

    def _kernel(x_ref, out_ref, send_sem, recv_sem):  # pragma: no cover
        my = jax.lax.axis_index(axis)
        right = jax.lax.rem(my + 1, jax.lax.axis_size(axis))
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=out_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=(right,),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()

    def shift(x):  # pragma: no cover - TPU only
        # one version-compat shim for shard_map, owned by mesh.py
        from paxi_tpu.parallel.mesh import _shard_map
        shard = functools.partial(
            _shard_map, mesh=mesh,
            in_specs=jax.sharding.PartitionSpec(axis),
            out_specs=jax.sharding.PartitionSpec(axis),
            check_rep=False)

        @shard
        def _shifted(xs):
            return pl.pallas_call(
                _kernel,
                out_shape=jax.ShapeDtypeStruct(xs.shape, xs.dtype),
                in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
                scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
            )(xs)

        return _shifted(x)

    return shift
