"""Open-loop shard ramp: aggregate cmds/s vs shard count.

The compartmentalization claim ("Bipartisan Paxos", "HT-Paxos",
PAPERS.md) made measurable end-to-end: a FIXED fleet of replicas is
partitioned into G independent consensus groups behind the shard
router, and the same Poisson open-loop ramp (host/benchmark.py) is
offered to the one router endpoint for G in {1, 2, 4}.  Aggregate
throughput rises with G because the bottleneck role — the group
leader, whose per-command replication work fans out to n-1 followers
— is replicated while each instance's fan-in shrinks (fleet/G - 1
followers per leader); past that the bottleneck visibly MOVES to the
shared router/serving tier, which is the papers' point.

Workers get **disjoint-then-crossing key ranges**: phase A pins each
worker's range inside one group (traffic partitions perfectly — the
scaling ceiling), phase B re-points every worker at a range STRIDING
all G groups (every worker hits every group through the same router
conns — the realistic mixed case).  Ranges stay disjoint across
workers in both phases, so each worker's per-key linearizability
verdict composes and the run-level anomaly count is their sum.

Every run ends with a burst of cross-shard transactions through the
router's 2PC path and an **atomicity oracle** sweep: for each txn,
linearizable readback of every op key must show the txn's writes
everywhere or nowhere (shard/txn.atomic_check).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List, Optional

from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.host.benchmark import OpenLoopBenchmark
from paxi_tpu.host.client import _Conn
from paxi_tpu.shard.cluster import ShardedCluster
from paxi_tpu.shard.txn import atomic_check


def _router_cfg(url: str) -> Config:
    """A one-entry Config so OpenLoopBenchmark can target the router
    like any node."""
    cfg = Config()
    cfg.addrs[ID("1.1")] = url
    cfg.http_addrs[ID("1.1")] = url
    return cfg


def worker_key_maps(shard_map, G: int, workers: int, K: int):
    """Per-worker injective key maps for both phases.

    disjoint: worker w draws from a K-key block inside group
    ``w % G``'s range.  crossing: worker w's j-th key lands in group
    ``j % G`` (upper half of each group's range, clear of the
    disjoint blocks), so every worker drives every group."""
    span = shard_map.span
    gsize = span // G
    maps = []
    kc = K // G + 1
    for w in range(workers):
        lo = (w % G) * gsize + (w // G) * K
        half = gsize // 2
        maps.append({
            "disjoint": (lambda j, _lo=lo: _lo + j),
            "crossing": (lambda j, _w=w, _g=G, _gs=gsize, _h=half,
                         _kc=kc: (j % _g) * _gs + _h + _w * _kc
                         + j // _g),
        })
    return maps


async def _txn_shots(router_url: str, shard_map, G: int, n_txns: int
                     ) -> Dict:
    """Cross-shard 2PC burst + atomicity oracle readback."""
    conn = _Conn(router_url)
    span, gsize = shard_map.span, shard_map.span // G
    committed = aborted = errors = 0
    shots = []
    try:
        for t in range(n_txns):
            # one fresh key per group, top slice of each range
            ops = [{"key": g * gsize + gsize - 512 + t,
                    "value": f"txn{t}:g{g}"} for g in range(G)]
            try:
                status, _, payload = await conn.request(
                    "POST", "/transaction",
                    {"Client-Id": "tpcshot",
                     "Command-Id": str(t + 1)},
                    json.dumps(ops).encode())
            except (IOError, OSError):
                errors += 1
                continue
            if status == 200:
                committed += 1
            else:
                aborted += 1
            shots.append(ops)
        atomic = violations = 0
        chk_cmd = 0
        for ops in shots:
            pairs: Dict[int, list] = {}
            for o in ops:
                # unique Command-Id per readback: a reused id would hit
                # the groups' at-most-once tables and replay the FIRST
                # readback's value, silently blinding the oracle
                chk_cmd += 1
                try:
                    st, _, obs = await conn.request(
                        "GET", f"/{o['key']}",
                        {"Client-Id": "tpcchk",
                         "Command-Id": str(chk_cmd)},
                        b"")
                except (IOError, OSError):
                    st, obs = 0, b""
                g = shard_map.group_of(o["key"])
                pairs.setdefault(g, []).append(
                    (o["value"].encode(), obs if st == 200 else b""))
            if atomic_check(pairs):
                atomic += 1
            else:
                violations += 1
    finally:
        conn.close()
    return {"txns": len(shots), "committed": committed,
            "aborted": aborted, "errors": errors, "atomic": atomic,
            "atomicity_violations": violations}


async def shard_ramp(algorithm: str = "paxos", shards: int = 2,
                     fleet: int = 12, workers: int = 4,
                     rates: Optional[List[float]] = None,
                     step_s: float = 3.0, K: int = 256, W: float = 0.5,
                     seed: int = 0, base_port: int = 18300,
                     txns: int = 8, lin: bool = True,
                     proc: bool = False, conns: int = 2,
                     drain_s: float = 4.0,
                     workload: str = "") -> Dict:
    """One G-point of the curve: ramp both phases, fire the 2PC burst,
    return the artifact row.

    ``workload``: name of a paxi_tpu/workload spec (e.g. hotrange,
    zipf99).  Adds a third "hot" phase where every worker draws keys
    from the spec's sampler and a LINEAR key map stretches [0, K) over
    the whole keyspace — the spec's hot ranks land inside group 0's
    range while the tail spreads across all groups, so skew shows up
    directly as per-group load imbalance in the router's
    ``paxi_router_group_commands_total`` counters (reported under
    ``router.group_commands`` with the hot group's share)."""
    G = shards
    if fleet % G:
        raise ValueError(f"fleet {fleet} not divisible into {G} groups")
    n = fleet // G
    rates = rates or [2000.0, 5000.0, 10000.0]
    sc = ShardedCluster(algorithm, groups=G, n=n, base_port=base_port,
                        router_port=base_port + 98, proc=proc)
    await sc.start()
    try:
        rcfg = _router_cfg(sc.router_url)
        maps = worker_key_maps(sc.map, G, workers, K)

        async def phase(name: str) -> List[Dict]:
            traj: List[Dict] = []
            sampler = asyncio.ensure_future(
                _gauge_sampler(sc.router, traj))
            try:
                outs = await asyncio.gather(*[
                    OpenLoopBenchmark(
                        rcfg, rates=[r / workers for r in rates],
                        step_s=step_s, seed=seed + 101 * w, conns=conns,
                        W=W, K=K, client_tag=f"{name[:1]}{w}w",
                        linearizability_check=lin, drain_s=drain_s,
                        key_map=maps[w][name]).run()
                    for w in range(workers)])
            finally:
                sampler.cancel()
                try:
                    await sampler
                except asyncio.CancelledError:
                    pass
            steps = []
            for i, r in enumerate(rates):
                steps.append({
                    "offered_ops_s": r,
                    "achieved_ops_s": round(sum(
                        o["steps"][i]["achieved_ops_s"]
                        for o in outs), 1),
                    "completed": sum(o["steps"][i]["completed"]
                                     for o in outs),
                    "errors": sum(o["steps"][i]["errors"]
                                  for o in outs),
                    "shed": sum(o["steps"][i]["shed"] for o in outs),
                    "latency_p50_ms": round(max(
                        o["steps"][i]["latency_ms"]["p50"]
                        for o in outs), 3),
                    "latency_p99_ms": round(max(
                        o["steps"][i]["latency_ms"]["p99"]
                        for o in outs), 3),
                })
            return [{"phase": name, "steps": steps,
                     "anomalies": (sum(o["anomalies"] or 0
                                       for o in outs) if lin else None),
                     "peak_ops_s": max(s["achieved_ops_s"]
                                       for s in steps),
                     "router_gauges": _traj_report(traj)}]

        phases = await phase("disjoint") + await phase("crossing")
        group_fwd_base: Dict[str, int] = {}
        if workload:
            # snapshot per-group counters BEFORE the hot phase so its
            # row reports only hot-phase routing, not the ramp's
            group_fwd_base = _group_counters(
                await sc.router.metrics_snapshot())
            phases += await _hot_phase(
                workload, rcfg, sc.map, rates, workers, step_s, seed,
                conns, W, K, drain_s)
        # G == 1 exercises the single-group packed-transaction path
        # (same surface, single-log atomicity); G > 1 runs real 2PC
        txn_report = await _txn_shots(sc.router_url, sc.map, G, txns) \
            if txns > 0 else None
        router_metrics = await sc.router.metrics_snapshot()
        peak = max(p["peak_ops_s"] for p in phases)
        router_report = {
            "forwards": _counter(router_metrics,
                                 "paxi_router_forwards_total"),
            "stale_reroutes": _counter(
                router_metrics, "paxi_router_stale_reroutes_total"),
            "map_swaps": _counter(router_metrics,
                                  "paxi_router_map_swaps_total"),
            "group_commands": _group_counters(router_metrics),
            # drained endpoint: both gauges must have settled to zero
            "pending_depth": _gauge_values(
                router_metrics, "paxi_router_pending_depth"),
            "inflight": _gauge_values(router_metrics,
                                      "paxi_router_inflight"),
        }
        if workload:
            total = _group_counters(router_metrics)
            hot = {g: total.get(g, 0) - group_fwd_base.get(g, 0)
                   for g in sorted(total)}
            hot_sum = sum(hot.values())
            router_report["hot_phase_group_commands"] = hot
            router_report["hot_group_share"] = round(
                max(hot.values()) / hot_sum, 3) if hot_sum else 0.0
        return {
            "mode": "shard-ramp",
            "algorithm": algorithm,
            "shards": G,
            "fleet": fleet,
            "replicas_per_group": n,
            "workers": workers,
            "W": W, "K": K,
            "cluster_proc": proc,
            **({"workload": workload} if workload else {}),
            "phases": phases,
            "aggregate_peak_ops_s": peak,
            "anomalies": (sum(p["anomalies"] or 0 for p in phases)
                          if lin else None),
            "txn": txn_report,
            "router": router_report,
        }
    finally:
        await sc.stop()


async def _hot_phase(wl_name: str, rcfg: Config, shard_map,
                     rates: List[float], workers: int, step_s: float,
                     seed: int, conns: int, W: float, K: int,
                     drain_s: float) -> List[Dict]:
    """Workload-driven phase: every worker samples the SAME named spec
    (distinct counter streams) and a linear key map stretches the
    spec's [0, K) key ids over the whole keyspace, concentrating the
    hot ranks inside group 0's range."""
    from paxi_tpu.workload import named_workload
    wl = named_workload(wl_name)
    stretch = max(shard_map.span // K, 1)
    outs = await asyncio.gather(*[
        OpenLoopBenchmark(
            rcfg, rates=[r / workers for r in rates], step_s=step_s,
            seed=seed + 307 * w, conns=conns, W=W, K=K,
            client_tag=f"h{w}w",
            # workers share the spec's key space (that is the point of
            # a hot range), so per-worker per-key histories are partial
            # and the per-worker linearizability verdict cannot compose
            linearizability_check=False, drain_s=drain_s,
            key_map=(lambda j, _s=stretch: j * _s),
            workload=wl, wl_stream=w).run()
        for w in range(workers)])
    steps = []
    for i, r in enumerate(rates):
        row = {
            "offered_ops_s": r,
            "achieved_ops_s": round(sum(
                o["steps"][i]["achieved_ops_s"] for o in outs), 1),
            "completed": sum(o["steps"][i]["completed"] for o in outs),
            "errors": sum(o["steps"][i]["errors"] for o in outs),
            "shed": sum(o["steps"][i]["shed"] for o in outs),
            "latency_p50_ms": round(max(
                o["steps"][i]["latency_ms"]["p50"] for o in outs), 3),
            "latency_p99_ms": round(max(
                o["steps"][i]["latency_ms"]["p99"] for o in outs), 3),
        }
        cls = {}
        for c in ("hot", "warm", "cold"):
            rows = [o["steps"][i]["key_class_latency"][c]
                    for o in outs
                    if c in o["steps"][i].get("key_class_latency", {})]
            if rows:
                cls[c] = {
                    "n": sum(x["n"] for x in rows),
                    "p50_ms": round(max(x["p50_ms"] for x in rows), 3),
                    "p99_ms": round(max(x["p99_ms"] for x in rows), 3),
                }
        if cls:
            row["key_class_latency"] = cls
        steps.append(row)
    return [{"phase": "hot", "workload": wl.name, "steps": steps,
             "anomalies": None,
             "peak_ops_s": max(s["achieved_ops_s"] for s in steps)}]


def _gauge_values(snap: Dict, name: str) -> Dict[str, float]:
    """Per-group gauge values keyed by the ``group`` label."""
    out: Dict[str, float] = {}
    for g in snap.get("gauges", []):
        if g["name"] == name:
            k = g.get("labels", {}).get("group", "?")
            out[k] = out.get(k, 0) + g["value"]
    return out


async def _gauge_sampler(router, out: List[Dict],
                         interval: float = 0.4) -> None:
    """Poll the router-tier gauges (per-group pending-queue depth +
    in-flight commands) while a phase's workers run, building the
    queue-trajectory evidence for WHERE the bottleneck sits: depth
    growing on one group = that group's leader saturating; depth flat
    while in-flight climbs = the shared router/serving tier."""
    t0 = time.monotonic()
    while True:
        snap = await router.metrics_snapshot()
        out.append({
            "t_s": round(time.monotonic() - t0, 2),
            "pending_depth": _gauge_values(
                snap, "paxi_router_pending_depth"),
            "inflight": _gauge_values(snap, "paxi_router_inflight"),
        })
        await asyncio.sleep(interval)


def _traj_report(traj: List[Dict], keep: int = 24) -> Dict:
    """Gauge trajectory -> artifact row: per-group maxima plus the
    (thinned) time series."""
    if not traj:
        return {"samples": 0}
    maxes: Dict[str, Dict[str, float]] = {"pending_depth": {},
                                          "inflight": {}}
    for s in traj:
        for kind in ("pending_depth", "inflight"):
            for g, v in s[kind].items():
                maxes[kind][g] = max(maxes[kind].get(g, 0), v)
    step = max(1, len(traj) // keep)
    return {"samples": len(traj),
            "max_pending_depth": {g: maxes["pending_depth"][g]
                                  for g in sorted(maxes["pending_depth"])},
            "max_inflight": {g: maxes["inflight"][g]
                             for g in sorted(maxes["inflight"])},
            "trajectory": traj[::step]}


def _counter(snap: Dict, name: str) -> int:
    return sum(c["value"] for c in snap.get("counters", [])
               if c["name"] == name)


def _group_counters(snap: Dict) -> Dict[str, int]:
    """Per-group routed-command totals keyed by the ``group`` label."""
    out: Dict[str, int] = {}
    for c in snap.get("counters", []):
        if c["name"] == "paxi_router_group_commands_total":
            g = c.get("labels", {}).get("group", "?")
            out[g] = out.get(g, 0) + c["value"]
    return out
