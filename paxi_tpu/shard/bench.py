"""Open-loop shard ramp: aggregate cmds/s vs shard count.

The compartmentalization claim ("Bipartisan Paxos", "HT-Paxos",
PAPERS.md) made measurable end-to-end: a FIXED fleet of replicas is
partitioned into G independent consensus groups behind the shard
router, and the same Poisson open-loop ramp (host/benchmark.py) is
offered to the one router endpoint for G in {1, 2, 4}.  Aggregate
throughput rises with G because the bottleneck role — the group
leader, whose per-command replication work fans out to n-1 followers
— is replicated while each instance's fan-in shrinks (fleet/G - 1
followers per leader); past that the bottleneck visibly MOVES to the
shared router/serving tier, which is the papers' point.

Workers get **disjoint-then-crossing key ranges**: phase A pins each
worker's range inside one group (traffic partitions perfectly — the
scaling ceiling), phase B re-points every worker at a range STRIDING
all G groups (every worker hits every group through the same router
conns — the realistic mixed case).  Ranges stay disjoint across
workers in both phases, so each worker's per-key linearizability
verdict composes and the run-level anomaly count is their sum.

Every run ends with a burst of cross-shard transactions through the
router's 2PC path and an **atomicity oracle** sweep: for each txn,
linearizable readback of every op key must show the txn's writes
everywhere or nowhere (shard/txn.atomic_check).

``migrate=True`` inserts a **migrate** phase after the ramp: paced
per-key-sequential traffic concentrates on group 0's range, a
Rebalancer reads the router's own load evidence to pick the split
point (deterministic midpoint fallback), and the coordinator streams
the NON-EMPTY range to the least-loaded group LIVE — under the
double-write fence, with per-key strict read-your-writes checking
through the whole window.  The phase row reports
``migration_blip_p99_ms`` (completion p99 inside the move window) vs
the steady-state p99, plus a seeded-keys readback oracle proving the
moved range arrived intact.  ``routers=N`` spreads the phase's
workers over N router endpoints (keys stay per-worker-disjoint, so
one key always flows through one router and the verdicts compose).
"""

from __future__ import annotations

import asyncio
import json
import random
import time
from typing import Dict, List, Optional

from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.host.benchmark import OpenLoopBenchmark
from paxi_tpu.host.client import _Conn
from paxi_tpu.shard.cluster import ShardedCluster
from paxi_tpu.shard.txn import atomic_check


def _router_cfg(url: str) -> Config:
    """A one-entry Config so OpenLoopBenchmark can target the router
    like any node."""
    cfg = Config()
    cfg.addrs[ID("1.1")] = url
    cfg.http_addrs[ID("1.1")] = url
    return cfg


def worker_key_maps(shard_map, G: int, workers: int, K: int):
    """Per-worker injective key maps for both phases.

    disjoint: worker w draws from a K-key block inside group
    ``w % G``'s range.  crossing: worker w's j-th key lands in group
    ``j % G`` (upper half of each group's range, clear of the
    disjoint blocks), so every worker drives every group."""
    span = shard_map.span
    gsize = span // G
    maps = []
    kc = K // G + 1
    for w in range(workers):
        lo = (w % G) * gsize + (w // G) * K
        half = gsize // 2
        maps.append({
            "disjoint": (lambda j, _lo=lo: _lo + j),
            "crossing": (lambda j, _w=w, _g=G, _gs=gsize, _h=half,
                         _kc=kc: (j % _g) * _gs + _h + _w * _kc
                         + j // _g),
        })
    return maps


async def _txn_shots(router_url: str, shard_map, G: int, n_txns: int
                     ) -> Dict:
    """Cross-shard 2PC burst + atomicity oracle readback."""
    conn = _Conn(router_url)
    span, gsize = shard_map.span, shard_map.span // G
    committed = aborted = errors = 0
    shots = []
    try:
        for t in range(n_txns):
            # one fresh key per group, top slice of each range
            ops = [{"key": g * gsize + gsize - 512 + t,
                    "value": f"txn{t}:g{g}"} for g in range(G)]
            try:
                status, _, payload = await conn.request(
                    "POST", "/transaction",
                    {"Client-Id": "tpcshot",
                     "Command-Id": str(t + 1)},
                    json.dumps(ops).encode())
            except (IOError, OSError):
                errors += 1
                continue
            if status == 200:
                committed += 1
            else:
                aborted += 1
            shots.append(ops)
        atomic = violations = 0
        chk_cmd = 0
        for ops in shots:
            pairs: Dict[int, list] = {}
            for o in ops:
                # unique Command-Id per readback: a reused id would hit
                # the groups' at-most-once tables and replay the FIRST
                # readback's value, silently blinding the oracle
                chk_cmd += 1
                try:
                    st, _, obs = await conn.request(
                        "GET", f"/{o['key']}",
                        {"Client-Id": "tpcchk",
                         "Command-Id": str(chk_cmd)},
                        b"")
                except (IOError, OSError):
                    st, obs = 0, b""
                g = shard_map.group_of(o["key"])
                pairs.setdefault(g, []).append(
                    (o["value"].encode(), obs if st == 200 else b""))
            if atomic_check(pairs):
                atomic += 1
            else:
                violations += 1
    finally:
        conn.close()
    return {"txns": len(shots), "committed": committed,
            "aborted": aborted, "errors": errors, "atomic": atomic,
            "atomicity_violations": violations}


async def shard_ramp(algorithm: str = "paxos", shards: int = 2,
                     fleet: int = 12, workers: int = 4,
                     rates: Optional[List[float]] = None,
                     step_s: float = 3.0, K: int = 256, W: float = 0.5,
                     seed: int = 0, base_port: int = 18300,
                     txns: int = 8, lin: bool = True,
                     proc: bool = False, conns: int = 2,
                     drain_s: float = 4.0,
                     workload: str = "", migrate: bool = False,
                     routers: int = 1) -> Dict:
    """One G-point of the curve: ramp both phases, fire the 2PC burst,
    return the artifact row.

    ``workload``: name of a paxi_tpu/workload spec (e.g. hotrange,
    zipf99).  Adds a third "hot" phase where every worker draws keys
    from the spec's sampler and a LINEAR key map stretches [0, K) over
    the whole keyspace — the spec's hot ranks land inside group 0's
    range while the tail spreads across all groups, so skew shows up
    directly as per-group load imbalance in the router's
    ``paxi_router_group_commands_total`` counters (reported under
    ``router.group_commands`` with the hot group's share)."""
    G = shards
    if fleet % G:
        raise ValueError(f"fleet {fleet} not divisible into {G} groups")
    if migrate and G < 2:
        raise ValueError("migrate phase needs at least 2 groups")
    n = fleet // G
    rates = rates or [2000.0, 5000.0, 10000.0]
    sc = ShardedCluster(algorithm, groups=G, n=n, base_port=base_port,
                        router_port=base_port + 98, proc=proc,
                        routers=routers)
    await sc.start()
    try:
        rcfg = _router_cfg(sc.router_url)
        maps = worker_key_maps(sc.map, G, workers, K)

        async def phase(name: str) -> List[Dict]:
            traj: List[Dict] = []
            sampler = asyncio.ensure_future(
                _gauge_sampler(sc.router, traj))
            try:
                outs = await asyncio.gather(*[
                    OpenLoopBenchmark(
                        rcfg, rates=[r / workers for r in rates],
                        step_s=step_s, seed=seed + 101 * w, conns=conns,
                        W=W, K=K, client_tag=f"{name[:1]}{w}w",
                        linearizability_check=lin, drain_s=drain_s,
                        key_map=maps[w][name]).run()
                    for w in range(workers)])
            finally:
                sampler.cancel()
                try:
                    await sampler
                except asyncio.CancelledError:
                    pass
            steps = []
            for i, r in enumerate(rates):
                steps.append({
                    "offered_ops_s": r,
                    "achieved_ops_s": round(sum(
                        o["steps"][i]["achieved_ops_s"]
                        for o in outs), 1),
                    "completed": sum(o["steps"][i]["completed"]
                                     for o in outs),
                    "errors": sum(o["steps"][i]["errors"]
                                  for o in outs),
                    "shed": sum(o["steps"][i]["shed"] for o in outs),
                    "latency_p50_ms": round(max(
                        o["steps"][i]["latency_ms"]["p50"]
                        for o in outs), 3),
                    "latency_p99_ms": round(max(
                        o["steps"][i]["latency_ms"]["p99"]
                        for o in outs), 3),
                })
            return [{"phase": name, "steps": steps,
                     "anomalies": (sum(o["anomalies"] or 0
                                       for o in outs) if lin else None),
                     "peak_ops_s": max(s["achieved_ops_s"]
                                       for s in steps),
                     "router_gauges": _traj_report(traj)}]

        phases = await phase("disjoint") + await phase("crossing")
        if migrate:
            phases += await _migrate_phase(
                sc, rate=rates[0], run_s=max(3 * step_s, 4.0),
                workers=workers, seed=seed)
        group_fwd_base: Dict[str, int] = {}
        if workload:
            # snapshot per-group counters BEFORE the hot phase so its
            # row reports only hot-phase routing, not the ramp's
            group_fwd_base = _group_counters(
                await sc.router.metrics_snapshot())
            phases += await _hot_phase(
                workload, rcfg, sc.map, rates, workers, step_s, seed,
                conns, W, K, drain_s)
        # G == 1 exercises the single-group packed-transaction path
        # (same surface, single-log atomicity); G > 1 runs real 2PC.
        # The oracle reads the ROUTER's live map: after a migrate
        # phase the boot map no longer describes ownership.
        txn_report = await _txn_shots(sc.router_url,
                                      sc.router.shard_map, G, txns) \
            if txns > 0 else None
        router_metrics = await sc.router.metrics_snapshot()
        peak = max(p["peak_ops_s"] for p in phases)
        router_report = {
            "forwards": _counter(router_metrics,
                                 "paxi_router_forwards_total"),
            "stale_reroutes": _counter(
                router_metrics, "paxi_router_stale_reroutes_total"),
            "map_swaps": _counter(router_metrics,
                                  "paxi_router_map_swaps_total"),
            "group_commands": _group_counters(router_metrics),
            # drained endpoint: both gauges must have settled to zero
            "pending_depth": _gauge_values(
                router_metrics, "paxi_router_pending_depth"),
            "inflight": _gauge_values(router_metrics,
                                      "paxi_router_inflight"),
        }
        if workload:
            total = _group_counters(router_metrics)
            hot = {g: total.get(g, 0) - group_fwd_base.get(g, 0)
                   for g in sorted(total)}
            hot_sum = sum(hot.values())
            router_report["hot_phase_group_commands"] = hot
            router_report["hot_group_share"] = round(
                max(hot.values()) / hot_sum, 3) if hot_sum else 0.0
        return {
            "mode": "shard-ramp",
            "algorithm": algorithm,
            "shards": G,
            "fleet": fleet,
            "replicas_per_group": n,
            "workers": workers,
            "W": W, "K": K,
            "cluster_proc": proc,
            **({"routers": routers} if routers > 1 else {}),
            **({"workload": workload} if workload else {}),
            "phases": phases,
            "aggregate_peak_ops_s": peak,
            "anomalies": (sum(p["anomalies"] or 0 for p in phases)
                          if lin else None),
            "txn": txn_report,
            "router": router_report,
        }
    finally:
        await sc.stop()


def _p(lat: List[float], q: float) -> float:
    if not lat:
        return 0.0
    s = sorted(lat)
    return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]


async def _migrate_phase(sc: ShardedCluster, rate: float,
                         run_s: float, workers: int,
                         seed: int) -> List[Dict]:
    """The live-migration phase: hot-range traffic, a mid-phase
    Rebalancer-chosen split + streamed move of a NON-EMPTY range, and
    the blip/oracle evidence for the artifact.

    Every worker owns a disjoint key set in the upper quarter of
    group 0's range and runs ONE op at a time (write then read-your-
    write), so each key has a single sequential client and a read
    returning anything but the last acked write is a hard anomaly —
    the strictest per-key check there is, held THROUGH the move
    window.  Oracle keys seeded above all traffic keys guarantee the
    moved slice is non-empty and its bytes survive the stream."""
    from paxi_tpu.shard.migrate import Rebalancer
    G, span = sc.G, sc.map.span
    gsize = span // G
    hot_hi = gsize                      # group 0's range is [0, gsize)
    urls = sc.router_urls
    # traffic keys: upper quarter of the hot range, per-worker blocks,
    # capped below the oracle strip
    base = (hot_hi * 3) // 4
    keys_of = [[base + w * 1024 + j * 8 for j in range(8)]
               for w in range(workers)]
    assert max(max(ks) for ks in keys_of) < hot_hi - 512
    # oracle keys: the very top of the range, above every traffic key,
    # so ANY load-median cut moves them — written once before the
    # move, untouched during it, read back after
    oracle = {hot_hi - 256 + i: f"mig-oracle-{i}".encode()
              for i in range(16)}
    conn = _Conn(sc.router_url)
    try:
        for i, (k, v) in enumerate(sorted(oracle.items())):
            st, _, _ = await conn.request(
                "PUT", f"/{k}", {"Client-Id": "migseed",
                                 "Command-Id": str(i + 1)}, v)
            if st != 200:
                raise RuntimeError(f"oracle seed write failed on {k}")
    finally:
        conn.close()

    t0 = time.monotonic()
    window = {"t_start": None, "t_end": None, "plan": None,
              "status": None, "fallback": False}
    stop = asyncio.Event()

    async def worker(w: int) -> Dict:
        wconn = _Conn(urls[w % len(urls)])
        rnd = random.Random(seed + 31 * w)
        vals: Dict[int, Optional[bytes]] = {}
        samples: List = []
        anomalies = errors = completed = 0
        cmd = 0
        per_op = workers / max(rate, 1.0)
        try:
            while not stop.is_set():
                k = rnd.choice(keys_of[w])
                cmd += 1
                t1 = time.monotonic()
                try:
                    if k not in vals or rnd.random() < 0.5:
                        v = f"w{w}c{cmd}".encode()
                        st, _, _ = await wconn.request(
                            "PUT", f"/{k}",
                            {"Client-Id": f"mg{w}",
                             "Command-Id": str(cmd)}, v)
                        if st == 200:
                            vals[k] = v
                        else:
                            # the write MAY have landed on one side:
                            # suspend this key's check until the next
                            # acked write re-anchors it
                            vals[k] = None
                            errors += 1
                    else:
                        st, _, obs = await wconn.request(
                            "GET", f"/{k}",
                            {"Client-Id": f"mg{w}",
                             "Command-Id": str(cmd)}, b"")
                        if st != 200:
                            errors += 1
                        elif vals.get(k) is not None \
                                and obs != vals[k]:
                            anomalies += 1
                except (IOError, OSError):
                    errors += 1
                    vals[k] = None
                t2 = time.monotonic()
                completed += 1
                samples.append((t2, (t2 - t1) * 1000.0))
                # fixed-interval pacing (closed loop + rate-derived
                # sleep): offered rate is approximate, which is fine
                # for a blip window — and no clock value ever steers
                # control flow (PXD141)
                await asyncio.sleep(per_op)
        finally:
            wconn.close()
        return {"samples": samples, "anomalies": anomalies,
                "errors": errors, "completed": completed,
                "vals": vals}

    async def mover() -> None:
        await asyncio.sleep(run_s * 0.3)
        # the split decision off the router's OWN evidence: command
        # deltas + the 64-bucket key histogram, with short hysteresis
        reb = Rebalancer(hot_share=0.5, min_ticks=2, min_cmds=10,
                         cooldown=0)
        sc.router.bucket_hits(reset=True)
        last = [c.value for c in sc.router._group_fwd]
        plan = None
        for _ in range(10):
            await asyncio.sleep(max(0.15, run_s * 0.02))
            cur = [c.value for c in sc.router._group_fwd]
            deltas = [a - b for a, b in zip(cur, last)]
            last = cur
            plan = reb.tick(sc.router.shard_map, deltas,
                            sc.router.bucket_hits(reset=True))
            if plan is not None:
                break
        if plan is None or plan.get("action") != "split" \
                or plan.get("src") != 0:
            # deterministic fallback: cut the hot range at the floor
            # of the traffic band so every live key moves too
            plan = {"action": "split", "lo": base - 64, "hi": hot_hi,
                    "src": 0, "dst": 1}
            window["fallback"] = True
        mig = sc.migrator(chunk=48)
        window["plan"] = plan
        window["t_start"] = time.monotonic()
        window["status"] = await mig.move_range(plan["lo"],
                                                plan["hi"],
                                                plan["dst"])
        window["t_end"] = time.monotonic()

    async def run() -> List[Dict]:
        tasks = [asyncio.ensure_future(worker(w))
                 for w in range(workers)]
        mv = asyncio.ensure_future(mover())
        await asyncio.sleep(run_s)
        try:
            await asyncio.wait_for(mv, timeout=60.0)
        finally:
            stop.set()
        return await asyncio.gather(*tasks)

    outs = await run()
    t_total = time.monotonic() - t0
    ws, we = window["t_start"], window["t_end"]
    in_win, steady = [], []
    for o in outs:
        for t, lat in o["samples"]:
            (in_win if ws is not None and ws <= t <= we
             else steady).append(lat)
    anomalies = sum(o["anomalies"] for o in outs)
    completed = sum(o["completed"] for o in outs)
    errors = sum(o["errors"] for o in outs)
    steady_p99 = round(_p(steady, 0.99), 3)
    blip_p99 = round(_p(in_win, 0.99), 3)

    # the migrated-range oracle: seeded keys must now route to dst
    # and read back byte-identical; live keys' last acked write must
    # read back too (the post-move readback half of the verdict)
    m_now = sc.router.shard_map
    plan = window["plan"]
    oracle_fail = moved_wrong = live_fail = 0
    conn = _Conn(sc.router_url)
    try:
        chk = 0
        for k, v in sorted(oracle.items()):
            chk += 1
            if m_now.group_of(k) != plan["dst"]:
                moved_wrong += 1
            st, _, obs = await conn.request(
                "GET", f"/{k}", {"Client-Id": "migchk",
                                 "Command-Id": str(chk)}, b"")
            if st != 200 or obs != v:
                oracle_fail += 1
        for o in outs:
            for k, v in sorted(o["vals"].items()):
                if v is None:
                    continue
                chk += 1
                st, _, obs = await conn.request(
                    "GET", f"/{k}", {"Client-Id": "migchk",
                                     "Command-Id": str(chk)}, b"")
                if st != 200 or obs != v:
                    live_fail += 1
    finally:
        conn.close()

    status = window["status"] or {}
    dualwrites = sum(
        r._dual_total.value
        for r in [sc.router] + [r for r, _ in sc.secondaries])
    return [{
        "phase": "migrate",
        "steps": [{
            "offered_ops_s": rate,
            "achieved_ops_s": round(completed / t_total, 1),
            "completed": completed,
            "errors": errors,
            "latency_p50_ms": round(_p(steady, 0.5), 3),
            "latency_p99_ms": steady_p99,
        }],
        "anomalies": anomalies,
        "peak_ops_s": round(completed / t_total, 1),
        "migration": {
            "plan": plan,
            "rebalancer_fallback": window["fallback"],
            "mid": status.get("mid"),
            "epoch": status.get("epoch"),
            "installed": status.get("installed"),
            "chunks": status.get("chunks"),
            "window_s": round((we - ws) if ws is not None else 0.0,
                              3),
            "window_samples": len(in_win),
            "steady_p99_ms": steady_p99,
            "migration_blip_p99_ms": blip_p99,
            "blip_ratio": round(blip_p99 / steady_p99, 3)
            if steady_p99 else None,
            "map_version": m_now.version,
            "dualwrites": dualwrites,
            "oracle": {
                "seeded_keys": len(oracle),
                "readback_failures": oracle_fail,
                "misrouted": moved_wrong,
                "live_readback_failures": live_fail,
                "clean": oracle_fail == moved_wrong == live_fail
                == 0,
            },
        },
    }]


async def _hot_phase(wl_name: str, rcfg: Config, shard_map,
                     rates: List[float], workers: int, step_s: float,
                     seed: int, conns: int, W: float, K: int,
                     drain_s: float) -> List[Dict]:
    """Workload-driven phase: every worker samples the SAME named spec
    (distinct counter streams) and a linear key map stretches the
    spec's [0, K) key ids over the whole keyspace, concentrating the
    hot ranks inside group 0's range."""
    from paxi_tpu.workload import named_workload
    wl = named_workload(wl_name)
    stretch = max(shard_map.span // K, 1)
    outs = await asyncio.gather(*[
        OpenLoopBenchmark(
            rcfg, rates=[r / workers for r in rates], step_s=step_s,
            seed=seed + 307 * w, conns=conns, W=W, K=K,
            client_tag=f"h{w}w",
            # workers share the spec's key space (that is the point of
            # a hot range), so per-worker per-key histories are partial
            # and the per-worker linearizability verdict cannot compose
            linearizability_check=False, drain_s=drain_s,
            key_map=(lambda j, _s=stretch: j * _s),
            workload=wl, wl_stream=w).run()
        for w in range(workers)])
    steps = []
    for i, r in enumerate(rates):
        row = {
            "offered_ops_s": r,
            "achieved_ops_s": round(sum(
                o["steps"][i]["achieved_ops_s"] for o in outs), 1),
            "completed": sum(o["steps"][i]["completed"] for o in outs),
            "errors": sum(o["steps"][i]["errors"] for o in outs),
            "shed": sum(o["steps"][i]["shed"] for o in outs),
            "latency_p50_ms": round(max(
                o["steps"][i]["latency_ms"]["p50"] for o in outs), 3),
            "latency_p99_ms": round(max(
                o["steps"][i]["latency_ms"]["p99"] for o in outs), 3),
        }
        cls = {}
        for c in ("hot", "warm", "cold"):
            rows = [o["steps"][i]["key_class_latency"][c]
                    for o in outs
                    if c in o["steps"][i].get("key_class_latency", {})]
            if rows:
                cls[c] = {
                    "n": sum(x["n"] for x in rows),
                    "p50_ms": round(max(x["p50_ms"] for x in rows), 3),
                    "p99_ms": round(max(x["p99_ms"] for x in rows), 3),
                }
        if cls:
            row["key_class_latency"] = cls
        steps.append(row)
    return [{"phase": "hot", "workload": wl.name, "steps": steps,
             "anomalies": None,
             "peak_ops_s": max(s["achieved_ops_s"] for s in steps)}]


def _gauge_values(snap: Dict, name: str) -> Dict[str, float]:
    """Per-group gauge values keyed by the ``group`` label."""
    out: Dict[str, float] = {}
    for g in snap.get("gauges", []):
        if g["name"] == name:
            k = g.get("labels", {}).get("group", "?")
            out[k] = out.get(k, 0) + g["value"]
    return out


async def _gauge_sampler(router, out: List[Dict],
                         interval: float = 0.4) -> None:
    """Poll the router-tier gauges (per-group pending-queue depth +
    in-flight commands) while a phase's workers run, building the
    queue-trajectory evidence for WHERE the bottleneck sits: depth
    growing on one group = that group's leader saturating; depth flat
    while in-flight climbs = the shared router/serving tier."""
    t0 = time.monotonic()
    while True:
        snap = await router.metrics_snapshot()
        out.append({
            "t_s": round(time.monotonic() - t0, 2),
            "pending_depth": _gauge_values(
                snap, "paxi_router_pending_depth"),
            "inflight": _gauge_values(snap, "paxi_router_inflight"),
        })
        await asyncio.sleep(interval)


def _traj_report(traj: List[Dict], keep: int = 24) -> Dict:
    """Gauge trajectory -> artifact row: per-group maxima plus the
    (thinned) time series."""
    if not traj:
        return {"samples": 0}
    maxes: Dict[str, Dict[str, float]] = {"pending_depth": {},
                                          "inflight": {}}
    for s in traj:
        for kind in ("pending_depth", "inflight"):
            for g, v in s[kind].items():
                maxes[kind][g] = max(maxes[kind].get(g, 0), v)
    step = max(1, len(traj) // keep)
    return {"samples": len(traj),
            "max_pending_depth": {g: maxes["pending_depth"][g]
                                  for g in sorted(maxes["pending_depth"])},
            "max_inflight": {g: maxes["inflight"][g]
                             for g in sorted(maxes["inflight"])},
            "trajectory": traj[::step]}


def _counter(snap: Dict, name: str) -> int:
    return sum(c["value"] for c in snap.get("counters", [])
               if c["name"] == name)


def _group_counters(snap: Dict) -> Dict[str, int]:
    """Per-group routed-command totals keyed by the ``group`` label."""
    out: Dict[str, int] = {}
    for c in snap.get("counters", []):
        if c["name"] == "paxi_router_group_commands_total":
            g = c.get("labels", {}).get("group", "?")
            out[g] = out.get(g, 0) + c["value"]
    return out
