"""Versioned key-range -> consensus-group mapping (the routing table).

Paxi's multi-leader layouts partition the key space statically per
deployment; the compartmentalization papers scale aggregate throughput
by adding independent instances of the bottleneck role behind such a
partition.  ``ShardMap`` is that partition as a VALUE: an immutable
list of contiguous ranges over a fixed key-space modulus, stamped with
a monotonically increasing ``version``.  Mutation (``move_range`` —
the control-plane half of wpaxos-style key stealing; data migration is
a follow-up) returns a NEW map with ``version + 1``; the router swaps
the reference under its lock (shard/router.py), so every routing
decision reads one consistent snapshot and a mid-pipeline bump is
detectable by epoch comparison (the stale-epoch reroute path).

Keys outside ``[0, span)`` fold in by modulo, so the unbounded int key
surface of the KV API routes deterministically.
"""

from __future__ import annotations

import bisect
import json
from dataclasses import dataclass
from typing import List, Tuple

DEFAULT_SPAN = 1 << 20


@dataclass(frozen=True)
class ShardMap:
    """``starts[i]`` begins the i-th range (``starts[0] == 0``); range
    i covers ``[starts[i], starts[i+1])`` (the last runs to ``span``)
    and is owned by ``groups[i]``."""

    version: int
    span: int
    starts: Tuple[int, ...]
    groups: Tuple[int, ...]

    @staticmethod
    def static(n_groups: int, span: int = DEFAULT_SPAN) -> "ShardMap":
        """The Paxi-style static layout: ``n_groups`` equal ranges."""
        if n_groups < 1 or span < n_groups:
            raise ValueError(f"bad shard layout: {n_groups} groups "
                             f"over span {span}")
        starts = tuple((span * g) // n_groups for g in range(n_groups))
        return ShardMap(version=1, span=span, starts=starts,
                        groups=tuple(range(n_groups)))

    @property
    def n_groups(self) -> int:
        return max(self.groups) + 1

    def group_of(self, key: int) -> int:
        """The owning group of ``key`` (modulo-folded into the span)."""
        k = int(key) % self.span
        return self.groups[bisect.bisect_right(self.starts, k) - 1]

    def ranges_of(self, group: int) -> List[Tuple[int, int]]:
        """The [lo, hi) ranges a group owns (diagnostics/migration)."""
        out = []
        for i, g in enumerate(self.groups):
            if g == group:
                hi = self.starts[i + 1] if i + 1 < len(self.starts) \
                    else self.span
                out.append((self.starts[i], hi))
        return out

    def move_range(self, lo: int, hi: int, group: int) -> "ShardMap":
        """A new map (version + 1) with ``[lo, hi)`` owned by
        ``group`` — the key-stealing control-plane primitive."""
        if not (0 <= lo < hi <= self.span):
            raise ValueError(f"bad range [{lo}, {hi}) over span "
                             f"{self.span}")
        if group < 0:
            raise ValueError(f"bad group {group}")
        points = sorted({*self.starts, lo, hi} - {self.span})
        starts: List[int] = []
        groups: List[int] = []
        for p in points:
            g = group if lo <= p < hi else self.group_of(p)
            if groups and groups[-1] == g:
                continue          # coalesce adjacent equal ranges
            starts.append(p)
            groups.append(g)
        return ShardMap(version=self.version + 1, span=self.span,
                        starts=tuple(starts), groups=tuple(groups))

    # ---- (de)serialization (the /shardmap wire form) -------------------
    def to_json(self) -> dict:
        return {"version": self.version, "span": self.span,
                "starts": list(self.starts), "groups": list(self.groups)}

    @staticmethod
    def from_json(d) -> "ShardMap":
        if isinstance(d, (str, bytes)):
            d = json.loads(d)
        m = ShardMap(version=int(d["version"]), span=int(d["span"]),
                     starts=tuple(int(s) for s in d["starts"]),
                     groups=tuple(int(g) for g in d["groups"]))
        m.validate()
        return m

    def validate(self) -> None:
        if not self.starts or self.starts[0] != 0 \
                or list(self.starts) != sorted(set(self.starts)) \
                or len(self.starts) != len(self.groups) \
                or self.starts[-1] >= self.span \
                or any(g < 0 for g in self.groups):
            raise ValueError(f"inconsistent ShardMap: {self.to_json()}")
