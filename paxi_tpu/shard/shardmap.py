"""Versioned key-range -> consensus-group mapping (the routing table).

Paxi's multi-leader layouts partition the key space statically per
deployment; the compartmentalization papers scale aggregate throughput
by adding independent instances of the bottleneck role behind such a
partition.  ``ShardMap`` is that partition as a VALUE: an immutable
list of contiguous ranges over a fixed key-space modulus, stamped with
a monotonically increasing ``version``.  Mutation (``move_range`` —
the control-plane half of wpaxos-style key stealing; data migration is
a follow-up) returns a NEW map with ``version + 1``; the router swaps
the reference under its lock (shard/router.py), so every routing
decision reads one consistent snapshot and a mid-pipeline bump is
detectable by epoch comparison (the stale-epoch reroute path).

Keys outside ``[0, span)`` fold in by modulo, so the unbounded int key
surface of the KV API routes deterministically.
"""

from __future__ import annotations

import bisect
import json
from dataclasses import dataclass, replace
from typing import List, Optional, Tuple

DEFAULT_SPAN = 1 << 20

# one in-flight range handoff: [lo, hi) moving from group ``src`` to
# group ``dst`` while ``src`` still OWNS the range (double-write
# window; shard/migrate.py)
Migration = Tuple[int, int, int, int]      # (lo, hi, src, dst)


@dataclass(frozen=True)
class ShardMap:
    """``starts[i]`` begins the i-th range (``starts[0] == 0``); range
    i covers ``[starts[i], starts[i+1])`` (the last runs to ``span``)
    and is owned by ``groups[i]``.  ``migrations`` lists the in-flight
    handoffs: ownership (and reads) stay with ``src``, but routers
    duplicate writes in the range to ``dst`` — the double-write window
    between a migration's fence and its cutover."""

    version: int
    span: int
    starts: Tuple[int, ...]
    groups: Tuple[int, ...]
    migrations: Tuple[Migration, ...] = ()

    @staticmethod
    def static(n_groups: int, span: int = DEFAULT_SPAN) -> "ShardMap":
        """The Paxi-style static layout: ``n_groups`` equal ranges."""
        if n_groups < 1 or span < n_groups:
            raise ValueError(f"bad shard layout: {n_groups} groups "
                             f"over span {span}")
        starts = tuple((span * g) // n_groups for g in range(n_groups))
        return ShardMap(version=1, span=span, starts=starts,
                        groups=tuple(range(n_groups)))

    @property
    def n_groups(self) -> int:
        return max(self.groups) + 1

    def group_of(self, key: int) -> int:
        """The owning group of ``key`` (modulo-folded into the span)."""
        k = int(key) % self.span
        return self.groups[bisect.bisect_right(self.starts, k) - 1]

    def ranges_of(self, group: int) -> List[Tuple[int, int]]:
        """The [lo, hi) ranges a group owns (diagnostics/migration)."""
        out = []
        for i, g in enumerate(self.groups):
            if g == group:
                hi = self.starts[i + 1] if i + 1 < len(self.starts) \
                    else self.span
                out.append((self.starts[i], hi))
        return out

    def move_range(self, lo: int, hi: int, group: int) -> "ShardMap":
        """A new map (version + 1) with ``[lo, hi)`` owned by
        ``group`` — the key-stealing control-plane primitive."""
        if not (0 <= lo < hi <= self.span):
            raise ValueError(f"bad range [{lo}, {hi}) over span "
                             f"{self.span}")
        if group < 0:
            raise ValueError(f"bad group {group}")
        points = sorted({*self.starts, lo, hi} - {self.span})
        starts: List[int] = []
        groups: List[int] = []
        for p in points:
            g = group if lo <= p < hi else self.group_of(p)
            if groups and groups[-1] == g:
                continue          # coalesce adjacent equal ranges
            starts.append(p)
            groups.append(g)
        return ShardMap(version=self.version + 1, span=self.span,
                        starts=tuple(starts), groups=tuple(groups),
                        migrations=self.migrations)

    # ---- live-migration control plane (shard/migrate.py) ---------------
    def migration_of(self, key: int) -> Optional[Migration]:
        """The in-flight handoff covering ``key`` (modulo-folded), or
        None — the router's double-write test, so it belongs to the
        fenced-read proof surface like ``group_of``."""
        k = int(key) % self.span
        for m in self.migrations:
            if m[0] <= k < m[1]:
                return m
        return None

    def with_migration(self, lo: int, hi: int, dst: int) -> "ShardMap":
        """A new map (version + 1) opening the double-write window for
        ``[lo, hi)`` toward ``dst``.  Ownership does NOT change — that
        is ``complete_migration`` — but routers seeing this map
        duplicate the range's writes to both groups."""
        if not (0 <= lo < hi <= self.span):
            raise ValueError(f"bad range [{lo}, {hi}) over span "
                             f"{self.span}")
        src = self.group_of(lo)
        if any(self.group_of(k) != src
               for k in self.starts if lo < k < hi):
            raise ValueError(f"range [{lo}, {hi}) spans several owner "
                             f"groups")
        if src == dst:
            raise ValueError(f"range [{lo}, {hi}) already owned by "
                             f"group {dst}")
        if any(m[0] < hi and lo < m[1] for m in self.migrations):
            raise ValueError(f"range [{lo}, {hi}) overlaps an "
                             f"in-flight migration")
        return replace(self, version=self.version + 1,
                       migrations=self.migrations + ((lo, hi, src,
                                                      dst),))

    def complete_migration(self, lo: int, hi: int) -> "ShardMap":
        """Cutover: a new map (version + 1) with ``[lo, hi)`` owned by
        its migration's ``dst`` and the window closed."""
        mig = next((m for m in self.migrations
                    if (m[0], m[1]) == (lo, hi)), None)
        if mig is None:
            raise ValueError(f"no in-flight migration for [{lo}, {hi})")
        rest = tuple(m for m in self.migrations if m is not mig)
        return replace(self.move_range(lo, hi, mig[3]),
                       migrations=rest)

    # ---- (de)serialization (the /shardmap wire form) -------------------
    def to_json(self) -> dict:
        d = {"version": self.version, "span": self.span,
             "starts": list(self.starts), "groups": list(self.groups)}
        if self.migrations:
            d["migrations"] = [list(m) for m in self.migrations]
        return d

    @staticmethod
    def from_json(d) -> "ShardMap":
        if isinstance(d, (str, bytes)):
            d = json.loads(d)
        m = ShardMap(version=int(d["version"]), span=int(d["span"]),
                     starts=tuple(int(s) for s in d["starts"]),
                     groups=tuple(int(g) for g in d["groups"]),
                     migrations=tuple(
                         tuple(int(x) for x in mg)
                         for mg in d.get("migrations", [])))
        m.validate()
        return m

    def validate(self) -> None:
        if not self.starts or self.starts[0] != 0 \
                or list(self.starts) != sorted(set(self.starts)) \
                or len(self.starts) != len(self.groups) \
                or self.starts[-1] >= self.span \
                or any(g < 0 for g in self.groups):
            raise ValueError(f"inconsistent ShardMap: {self.to_json()}")
        for lo, hi, src, dst in self.migrations:
            if not (0 <= lo < hi <= self.span) or src == dst \
                    or dst < 0 or self.group_of(lo) != src \
                    or any(self.group_of(k) != src
                           for k in self.starts if lo < k < hi):
                raise ValueError(
                    f"inconsistent migration ({lo}, {hi}, {src}, "
                    f"{dst}) in ShardMap: {self.to_json()}")
