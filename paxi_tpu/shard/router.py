"""The shard router tier: one serving surface over G consensus groups.

Clients speak the ordinary KV REST dialect to the router; the router
resolves each key through the versioned ``ShardMap`` and forwards the
request over pipelined connections (host/client._Conn) to the owning
group's entry node — so the whole existing serving stack (pipelined
HTTP, batch-per-slot commit pipeline, per-command reply fan-out) sits
unchanged BEHIND the partition, and aggregate throughput scales with
independent group instances instead of one leader pipeline.

Routing-table swap discipline (the PXC-checked shape): ``_map`` and
the per-group pending queues are guarded by one ``threading.Lock``;
``install_map`` swaps the immutable ShardMap reference under it and
every request path reads one snapshot.  Forwarding is two-phase like
the batch buffer: requests enqueue (under the lock) onto the owning
group's pending list stamped with the map version they resolved
under; a scheduled flush swaps the lists out under the lock and ships
them outside it.  The flush RE-RESOLVES any op whose stamp predates
the current map version — an op whose key moved groups mid-pipeline
is rerouted to its new owner (counted as
``paxi_router_stale_reroutes_total``) instead of executing against a
group that no longer owns the key: the stale-epoch reject + retry
path, internal to the router so clients never see a misrouted reply.

Live migration (shard/migrate.py) adds two flush-time behaviors: a
write whose key sits in a migration window of the current map ships
to BOTH owner groups and acks only when both legs ack (the
double-write fence, ``paxi_router_dualwrites_total``); a backend
reply carrying the MOVED marker (the key's range was released at
cutover) re-enqueues the op under the freshest map — refreshed via
the injectable ``_map_refresh`` hook on secondary routers — instead
of surfacing stale state, so N stateless routers can share one
versioned map with only the primary seeing ``install_map`` directly.

Surfaces:
- ``GET|PUT|POST /{key}``          routed KV (Client-Id/Command-Id pass
                                   through, so at-most-once filtering
                                   and linearizability hold end-to-end)
- ``POST /transaction``            single-group txns forward as packed
                                   transactions; cross-group txns run
                                   2PC (shard/txn.py)
- ``GET /shardmap``                the live map (version, ranges)
- ``POST /shardmap/move?lo&hi&group``  key-stealing control plane:
                                   swap in ``map.move_range(...)``
- ``GET /metrics``                 router registry + every group's
                                   node registries, each group's
                                   series labeled ``group=<g>``,
                                   merged through the ONE registry
                                   code path (metrics/registry.py)
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from paxi_tpu.core.command import MOVED_MAGIC, RESERVED_PREFIXES
from paxi_tpu.host.client import _Conn
from paxi_tpu.host.http import _OK_TMPL, _response, read_request
from paxi_tpu.metrics import Registry, merge_snapshots
from paxi_tpu.metrics.registry import render_prometheus
from paxi_tpu.obs import (SpanCollector, TraceCtx, new_trace_id,
                          process_sampler)
from paxi_tpu.obs import merge as merge_spans
from paxi_tpu.obs import label_group as label_group_spans
from paxi_tpu.shard.shardmap import ShardMap
from paxi_tpu.shard.txn import ShardCoordinator, TxnOutcome, partition_ops


class _RoutedOp:
    """One forwarded KV request: the backend frame, the response slot,
    the map epoch it was routed under, and the pending-queue ``route``
    span when the request is traced.  ``write`` marks ops that must be
    duplicated inside a double-write window; ``dual`` marks a leg of
    such a duplicated write (its slot resolves to a raw
    ``(status, payload)`` pair joined by ``_dual_join``); ``tries``
    counts MOVED-marker bounces (shard/migrate.py cutover)."""

    __slots__ = ("key", "frame", "slot", "epoch", "span", "write",
                 "tries", "dual")

    def __init__(self, key: int, frame: bytes, slot, epoch: int,
                 span=None, write: bool = False):
        self.key = key
        self.frame = frame
        self.slot = slot
        self.epoch = epoch
        self.span = span
        self.write = write
        self.tries = 0
        self.dual = False


class ShardRouter:
    """Routing core: map snapshot/swaps, per-group pipes, 2PC."""

    def __init__(self, shard_map: ShardMap, group_urls: List[str],
                 lease_s: float = 0.2,
                 metrics: Optional[Registry] = None,
                 group_scrape=None, group_scrape_spans=None):
        if shard_map.n_groups > len(group_urls):
            raise ValueError(
                f"map names group {shard_map.n_groups - 1} but only "
                f"{len(group_urls)} group urls given")
        self._lock = threading.Lock()
        self._map = shard_map
        self._pending: List[List[_RoutedOp]] = [[] for _ in group_urls]
        self._flush_scheduled = False
        self._conns = [_Conn(u) for u in group_urls]
        self._tpc_conns = [_Conn(u) for u in group_urls]
        self.metrics = metrics if metrics is not None \
            else Registry(tier="router")
        # async callable returning per-group registry snapshots for
        # /metrics aggregation (injected by ShardedCluster: in-proc
        # reads replica registries, subprocess mode scrapes HTTP);
        # _group_scrape_spans is the same shape for GET /spans
        self._group_scrape = group_scrape
        self._group_scrape_spans = group_scrape_spans
        # the router is the entry tier of sharded serving: head-based
        # sampling happens here (obs/sample.py), once per command, and
        # the decision propagates to the backend group as a
        # Property-Trace header — backend nodes never re-sample
        self.sampler = process_sampler()
        self.spans = SpanCollector(node="router")
        self._fwd_total = self.metrics.counter(
            "paxi_router_forwards_total")
        self._stale_total = self.metrics.counter(
            "paxi_router_stale_reroutes_total")
        self._map_swaps = self.metrics.counter(
            "paxi_router_map_swaps_total")
        self._dual_total = self.metrics.counter(
            "paxi_router_dualwrites_total")
        # optional async hook a multi-router deployment injects
        # (cluster.py): fetch + install the primary's current map when
        # a backend bounces a request with the MOVED marker — how a
        # stale secondary router converges on a cutover it missed
        self._map_refresh = None
        # 64-bucket key histogram over the map span: the rebalancer's
        # split-point evidence (which part of a hot range is hot),
        # maintained under the routing lock so it reads one map
        # snapshot per increment
        self._bucket_hits = [0] * 64
        # per-group routed-command load: the skew evidence for
        # workload-driven runs (a hot key range shows up as one group's
        # counter racing ahead of the rest) — same registry path as
        # every other series, so /metrics and shard/bench.py read it
        # without a side channel
        self._group_fwd = [
            self.metrics.counter("paxi_router_group_commands_total",
                                 group=str(g))
            for g in range(len(group_urls))]
        # router-tier levels, per group: how deep the pending queue is
        # right now and how many shipped commands await group replies —
        # the "router-capped past G=2" claim as scrapeable numbers
        self._g_depth = [
            self.metrics.gauge("paxi_router_pending_depth",
                               group=str(g))
            for g in range(len(group_urls))]
        self._g_inflight = [
            self.metrics.gauge("paxi_router_inflight", group=str(g))
            for g in range(len(group_urls))]
        self.coord = ShardCoordinator(self._tpc_submit, lease_s=lease_s,
                                      metrics=self.metrics,
                                      spans=self.spans)

    # ---- map snapshot / swap (the lockset-checked pair) ----------------
    @property
    def shard_map(self) -> ShardMap:
        with self._lock:
            return self._map

    def install_map(self, new_map: ShardMap) -> None:
        """Swap the routing table (version must advance).  Pending ops
        re-resolve at the next flush — nothing here touches in-flight
        state beyond the one reference swap."""
        new_map.validate()
        if new_map.n_groups > len(self._conns):
            raise ValueError(
                f"map names group {new_map.n_groups - 1} but the "
                f"router has {len(self._conns)} groups")
        with self._lock:
            if new_map.version <= self._map.version:
                raise ValueError(
                    f"stale map: version {new_map.version} <= "
                    f"installed {self._map.version}")
            self._map = new_map
        self._map_swaps.inc()

    # ---- KV forwarding --------------------------------------------------
    def sample_entry(self, kind: str, **labels):
        """The once-per-command sampling decision: a hit opens (and
        returns) the trace's root span; None == unsampled."""
        if not self.sampler.decide():
            return None
        return self.spans.start(kind, TraceCtx(new_trace_id()),
                                **labels)

    def route_kv(self, key: int, frame: bytes, loop,
                 span=None, write: bool = False) -> asyncio.Future:
        """Enqueue one KV request for its owning group; the returned
        future resolves to response BYTES for the router's client.
        ``span`` is the traced request's root (sample_entry): its
        pending-queue wait becomes a ``route`` child span and the root
        finishes when the response slot resolves.  ``write`` ops are
        duplicated to the destination group at flush time when their
        key sits in a double-write window (shard/migrate.py)."""
        slot: asyncio.Future = loop.create_future()
        self._fwd_total.inc()
        op = _RoutedOp(key, frame, slot, 0, write=write)
        with self._lock:
            m = self._map
            g = m.group_of(key)
            op.epoch = m.version
            self._pending[g].append(op)
            depth = len(self._pending[g])
            self._bucket_hits[(int(key) % m.span) * 64 // m.span] += 1
        self._g_depth[g].set(depth)
        self._group_fwd[g].inc()
        if span is not None:
            op.span = self.spans.start("route", span.child(),
                                       group=str(g))
            spans = self.spans
            slot.add_done_callback(
                lambda _s, _sp=span: spans.finish(_sp))
        return slot

    async def flush(self) -> None:
        """Ship every pending op: swap the queues out under the lock,
        re-resolve stale-epoch ops against the CURRENT map (rerouting
        moved keys to their new owner), then write each group's burst
        over its pipelined connection."""
        with self._lock:
            m = self._map
            batches = self._pending
            self._pending = [[] for _ in self._conns]
        for gd in self._g_depth:
            gd.set(0)
        moved: List[_RoutedOp] = []
        for g, ops in enumerate(batches):
            if not ops:
                continue
            keep: List[_RoutedOp] = []
            for op in ops:
                if op.epoch != m.version and m.group_of(op.key) != g:
                    op.epoch = m.version
                    moved.append(op)
                else:
                    keep.append(op)
            batches[g] = keep
        for op in moved:
            self._stale_total.inc()
            g_new = m.group_of(op.key)
            self._group_fwd[g_new].inc()   # load lands on the new owner
            batches[g_new].append(op)
        # double-write fence: a write whose key sits in one of the
        # CURRENT map's migration windows ships to BOTH groups — the
        # client slot resolves only once both legs acked (_dual_join),
        # so an acked write can never exist on just one side of the
        # handoff
        for g, ops in enumerate(batches):
            for op in ops:
                if not op.write or op.dual:
                    continue
                mig = m.migration_of(op.key)
                if mig is None or mig[2] != g:
                    continue
                client = op.slot
                fa = client.get_loop().create_future()
                fb = client.get_loop().create_future()
                op.slot, op.dual = fa, True
                shadow = _RoutedOp(op.key, op.frame, fb, m.version,
                                   write=True)
                shadow.dual = True
                self._dual_total.inc()
                self._group_fwd[mig[3]].inc()
                batches[mig[3]].append(shadow)
                self._dual_join(client, fa, fb)
        await asyncio.gather(*[
            self._ship(g, ops) for g, ops in enumerate(batches) if ops])

    async def _ship(self, g: int, ops: List[_RoutedOp]) -> None:
        conn = self._conns[g]
        try:
            await conn.ensure()
        except OSError as e:
            for op in ops:
                self.spans.finish(op.span)
                self._fail_op(op, e)
            return
        self._g_inflight[g].inc(len(ops))
        for op in ops:
            self.spans.finish(op.span)   # queue wait ends at the wire
            conn.submit_raw(op.frame, self._make_done(op, g))
        try:
            await conn.flush()
        except (ConnectionError, OSError):
            pass   # the dead reader task fails the waiters; next
            # flush re-dials via ensure()

    @staticmethod
    def _fail_slot(slot: asyncio.Future, exc: Exception) -> None:
        if not slot.done():
            slot.set_result(_response(
                500, b"", {"Err": f"group unreachable: {exc!r}"}))

    def _fail_op(self, op: _RoutedOp, exc: Exception) -> None:
        if op.slot.done():
            return
        if op.dual:
            op.slot.set_result((599, repr(exc).encode()))
        else:
            self._fail_slot(op.slot, exc)

    @staticmethod
    def _dual_join(client: asyncio.Future, fa: asyncio.Future,
                   fb: asyncio.Future) -> None:
        """Resolve the client slot once BOTH double-write legs are in:
        either leg failing fails the request (the client must never
        believe an un-duplicated write acked); the source group's
        payload (the authoritative previous value) answers, unless the
        source already released the range (MOVED marker — cutover
        raced the ship), in which case the destination ack stands."""
        def done(_f):
            if not (fa.done() and fb.done()) or client.done():
                return
            (sa, pa), (sb, pb) = fa.result(), fb.result()
            if sa != 200 or sb != 200:
                err = pa if sa != 200 else pb
                client.set_result(_response(
                    500, b"",
                    {"Err": "double-write leg failed: "
                     + err.decode("latin1")}))
                return
            payload = pb if pa.startswith(MOVED_MAGIC) else pa
            client.set_result(_OK_TMPL % len(payload) + payload)
        fa.add_done_callback(done)
        fb.add_done_callback(done)

    def _make_done(self, op: _RoutedOp, g: int):
        inflight = self._g_inflight[g]

        def done(status, headers, payload, exc, _op=op):
            inflight.dec()
            slot = _op.slot
            if slot.done():
                return
            if _op.dual:
                # one leg of a double-write: hand the raw outcome to
                # _dual_join, which picks the client reply
                if exc is not None:
                    slot.set_result((599, repr(exc).encode()))
                elif status == 200:
                    slot.set_result((200, payload))
                else:
                    slot.set_result(
                        (status, headers.get("err", "").encode()))
                return
            if exc is not None:
                ShardRouter._fail_slot(slot, exc)
            elif status == 200 and payload.startswith(MOVED_MAGIC):
                # the group released this key's range to a new owner
                # (post-cutover): reroute under the current map
                # instead of surfacing the marker
                self._bounce(_op)
            elif status == 200:
                slot.set_result(_OK_TMPL % len(payload) + payload)
            else:
                slot.set_result(_response(
                    status, b"", {"Err": headers.get("err", "")}))
        return done

    # ---- MOVED bounce (stale router vs. cutover) ------------------------
    def _bounce(self, op: _RoutedOp) -> None:
        op.tries += 1
        if op.tries > 3:
            op.slot.set_result(_response(
                500, b"", {"Err": "range moved; reroute retries "
                                  "exhausted"}))
            return
        self._stale_total.inc()
        op.slot.get_loop().create_task(self._rebounce(op))

    async def _rebounce(self, op: _RoutedOp) -> None:
        """Re-enqueue a MOVED-bounced op under the freshest map: pull
        the primary's map first when the refresh hook is wired (a
        stale secondary router learning of a cutover it missed), then
        re-resolve and ship."""
        if self._map_refresh is not None:
            try:
                await self._map_refresh()
            except (IOError, OSError, ValueError):
                pass   # refresh failing just burns one retry
        with self._lock:
            m = self._map
            g = m.group_of(op.key)
            op.epoch = m.version
            self._pending[g].append(op)
        self._group_fwd[g].inc()
        await self.flush()

    async def barrier(self, group: int) -> None:
        """Write-order fence for ``group``: every KV op this router
        already accepted for the group is on its wire (and therefore
        ahead in its log) before this returns — flush the pending
        queue, then ride a no-op read through the SAME pipelined
        connection, whose FIFO ordering makes the read's reply prove
        the earlier writes were submitted.  The migration coordinator
        calls this before committing a fence record so the fence
        orders after every pre-fence routed write."""
        await self.flush()
        conn = self._conns[group]
        await conn.ensure()
        slot = asyncio.get_running_loop().create_future()

        def done(status, headers, payload, exc):
            if not slot.done():
                slot.set_result(b"")
        conn.submit_raw(
            b"GET /0 HTTP/1.1\r\nContent-Length: 0\r\n"
            b"Client-Id: \r\nCommand-Id: 0\r\n\r\n", done)
        await conn.flush()
        await slot

    def bucket_hits(self, reset: bool = False) -> List[int]:
        """The 64-bucket key histogram snapshot (rebalancer input)."""
        with self._lock:
            out = list(self._bucket_hits)
            if reset:
                self._bucket_hits = [0] * 64
        return out

    # ---- 2PC transport --------------------------------------------------
    async def _tpc_submit(self, group: int, key: int, rec: dict):
        """ShardCoordinator transport: one 2PC record as POST /tpc to
        the group (dedicated conns — records must not queue behind a
        KV burst in the shared pipeline); the server packs the
        TPC_MAGIC form, so the record is encoded once per hop."""
        doc: Dict = {"kind": rec["kind"], "txid": rec["txid"],
                     "key": int(key)}
        if "ops" in rec:
            doc["ops"] = [[k, v.decode("latin1")] for k, v in rec["ops"]]
        if rec.get("outcome"):
            doc["outcome"] = rec["outcome"]
        if rec.get("trace"):
            # the coordinator's record-span context: the participant
            # group's tpc/batch/quorum/exec spans stitch under it
            doc["trace"] = rec["trace"]
        body = json.dumps(doc).encode()
        conn = self._tpc_conns[group]
        try:
            status, _, payload = await conn.request(
                "POST", "/tpc", {}, body)
            return status == 200, payload
        except (IOError, OSError) as e:
            return False, repr(e).encode()

    async def run_transaction(self, ops, client_id: str,
                              command_id: int, trace=None) -> bytes:
        """POST /transaction: partition by the current map; one group
        -> forward the packed transaction unchanged (single-log
        atomicity); several -> 2PC.  ``trace`` is the sampled
        transaction's root context — single-group it rides the
        Property-Trace header, cross-group the coordinator parents its
        per-record spans under it."""
        m = self.shard_map
        parts = partition_ops(m, ops)
        if len(parts) == 1:
            ((g, gops),) = parts.items()
            body = json.dumps([
                {"key": k, "value": v.decode("latin1")}
                for k, v in gops]).encode()
            hdrs = {"Client-Id": client_id,
                    "Command-Id": str(command_id)}
            if trace is not None:
                hdrs["Property-Trace"] = trace.encode()
            conn = self._tpc_conns[g]
            try:
                status, headers, payload = await conn.request(
                    "POST", "/transaction", hdrs, body)
            except (IOError, OSError) as e:
                return _response(500, b"", {"Err": repr(e)})
            if status != 200:
                return _response(status, b"",
                                 {"Err": headers.get("err", "")})
            return _OK_TMPL % len(payload) + payload
        try:
            out: TxnOutcome = await self.coord.run_txn(parts,
                                                       trace=trace)
        except (IOError, OSError) as e:
            # decide unreachable: the outcome is UNKNOWN (participants
            # may hold stages until a recover() pass) — answer 500
            # rather than letting the exception tear the client
            # connection down with its pipeline
            return _response(500, b"",
                             {"Err": f"2pc outcome unknown: {e}"})
        if not out.committed:
            return _response(500, b"", {"Err": out.err or "aborted"})
        # re-assemble prepare-point previous values into op order
        cursor = {g: iter(vals) for g, vals in out.values.items()}
        values = [next(cursor[m.group_of(k)]) for k, _ in ops]
        payload = json.dumps(
            {"ok": True, "txid": out.txid,
             "values": [v.decode("latin1") for v in values]}).encode()
        return _OK_TMPL % len(payload) + payload

    # ---- metrics aggregation -------------------------------------------
    async def metrics_snapshot(self) -> Dict:
        snaps = [self.metrics.snapshot()]
        if self._group_scrape is not None:
            per_group = await self._group_scrape()
            for g, gsnaps in enumerate(per_group):
                for s in gsnaps:
                    snaps.append(label_group(s, g))
        return merge_snapshots(snaps)

    async def spans_snapshot(self) -> List[Dict]:
        """Router spans + every group's node spans, each group's spans
        stamped ``group=<g>`` — the span analog of metrics_snapshot,
        and where a cross-shard 2PC becomes ONE stitched tree: the
        coordinator's record spans (here) and the participant spans
        (scraped) share the transaction's trace id."""
        lists = [self.spans.export()]
        if self._group_scrape_spans is not None:
            per_group = await self._group_scrape_spans()
            for g, gspans in enumerate(per_group):
                lists.append(label_group_spans(gspans, g))
        return merge_spans(lists)

    def close(self) -> None:
        for c in self._conns + self._tpc_conns:
            c.close()


def label_group(snap: Dict, group: int) -> Dict:
    """Stamp ``group=<g>`` into every series of a registry snapshot —
    the ONE aggregation convention for per-group observability."""
    g = str(group)
    return {
        "counters": [dict(c, labels={**c.get("labels", {}), "group": g})
                     for c in snap.get("counters", [])],
        "gauges": [dict(gg, labels={**gg.get("labels", {}),
                                    "group": g})
                   for gg in snap.get("gauges", [])],
        "histograms": [dict(h, labels={**h.get("labels", {}),
                                       "group": g})
                       for h in snap.get("histograms", [])],
    }


class RouterServer:
    """The router's client-facing HTTP endpoint: a pipelined reader/
    writeback pair (host/http.py's split, sized down) whose KV hot
    path enqueues onto the routing core and flushes once per parsed
    burst."""

    PIPELINE_DEPTH = 1024
    REQUEST_TIMEOUT = 10.0

    def __init__(self, router: ShardRouter, addr: str):
        import uuid
        self.router = router
        self.addr = addr
        self._server = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._txn_seq = 0
        # fallback client identity for transactions sent WITHOUT a
        # Client-Id header: unique per router instance, so a router
        # restart (which resets _txn_seq) can never collide with a
        # long-lived group's at-most-once table entries for the old
        # instance's identity
        self._txn_cid = f"router-{uuid.uuid4().hex[:10]}"

    async def start(self) -> None:
        from paxi_tpu.host.transport import parse_addr
        self._loop = asyncio.get_running_loop()
        _, host, port = parse_addr(self.addr)
        self._server = await asyncio.start_server(self._serve, host,
                                                  port)

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        self.router.close()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        pending: asyncio.Queue = asyncio.Queue(
            maxsize=self.PIPELINE_DEPTH)
        wtask = asyncio.create_task(self._writeback(pending, writer))
        try:
            while True:
                method, path, headers, body = await read_request(reader)
                slot = await self._route(method, path, headers, body)
                await pending.put(slot)
                if getattr(reader, "_buffer", b""):
                    continue   # more pipelined requests already
                    # buffered: parse them into the same flush
                await self.router.flush()
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            try:
                await self.router.flush()
            except (ConnectionError, OSError):
                pass
            await pending.put(None)
            await wtask
            writer.close()

    async def _writeback(self, pending: asyncio.Queue,
                         writer: asyncio.StreamWriter) -> None:
        out: List[bytes] = []
        broken = False
        while True:
            slot = await pending.get()
            if slot is None:
                break
            if not isinstance(slot, bytes):
                try:
                    slot = await asyncio.wait_for(
                        slot, timeout=self.REQUEST_TIMEOUT)
                except asyncio.TimeoutError:
                    slot = _response(500, b"",
                                     {"Err": "request timed out"})
            out.append(slot)
            if pending.empty() and out and not broken:
                data = b"".join(out)
                out.clear()
                try:
                    writer.write(data)
                    await writer.drain()
                except (ConnectionError, OSError):
                    broken = True

    async def _route(self, method: str, path: str,
                     headers: Dict[str, str], body: bytes):
        url = urlparse(path)
        parts = [p for p in url.path.split("/") if p]
        # the KV hot shape first
        if len(parts) == 1 and method in ("GET", "PUT", "POST"):
            try:
                key = int(parts[0])
            except ValueError:
                return await self._route_slow(method, url, parts,
                                              headers, body)
            value = body if method in ("PUT", "POST") else b""
            if value.startswith(RESERVED_PREFIXES):
                return _response(400, b"",
                                 {"Err": "reserved value prefix"})
            head = [f"{method} /{key} HTTP/1.1",
                    f"Content-Length: {len(value)}",
                    f"Client-Id: {headers.get('client-id', '')}",
                    f"Command-Id: {headers.get('command-id', '0')}"]
            sp = self.router.sample_entry("request", key=str(key))
            if sp is not None:
                # the one place sampling costs anything: the extra
                # header pushes the backend frame off its 4-line fast
                # parse onto the (still cheap) slow path — for sampled
                # requests only
                head.append(f"Property-Trace: {sp.child().encode()}")
            frame = ("\r\n".join(head) + "\r\n\r\n").encode() + value
            return self.router.route_kv(key, frame, self._loop,
                                        span=sp,
                                        write=len(value) > 0)
        return await self._route_slow(method, url, parts, headers, body)

    async def _route_slow(self, method: str, url, parts,
                          headers: Dict[str, str], body: bytes):
        r = self.router
        # per-session ordering: KV ops this connection pipelined ahead
        # of a slow request (e.g. a transaction touching the same key)
        # must reach their groups BEFORE the slow path runs — a
        # transaction completing first would be overwritten by the
        # earlier op's late flush
        await r.flush()
        if parts and parts[0] == "transaction":
            if method != "POST":
                return _response(405, b"", {"Err": "POST only"})
            self._txn_seq += 1
            try:
                ops = [(int(o["key"]),
                        o.get("value", "").encode("latin1"))
                       for o in json.loads(body.decode() or "[]")]
                if not ops:
                    raise ValueError("empty transaction")
                cmd_id = int(headers.get("command-id",
                                         str(self._txn_seq)))
            except (ValueError, KeyError, TypeError,
                    AttributeError) as e:
                return _response(400, b"", {"Err": repr(e)})
            if any(v.startswith(RESERVED_PREFIXES) for _, v in ops):
                # a reserved-prefix op value would execute as a 2PC/
                # migration record at every participant — refuse at
                # the router exactly like the KV surface above
                return _response(400, b"",
                                 {"Err": "reserved value prefix"})
            sp = r.sample_entry("txn", ops=str(len(ops)))
            try:
                return await r.run_transaction(
                    ops, headers.get("client-id", self._txn_cid),
                    cmd_id,
                    trace=None if sp is None else sp.child())
            finally:
                r.spans.finish(sp)
        if parts and parts[0] == "shardmap":
            if len(parts) == 1 and method == "GET":
                return _response(
                    200, json.dumps(r.shard_map.to_json()).encode(),
                    {"Content-Type": "application/json"})
            if len(parts) == 2 and parts[1] == "move" \
                    and method == "POST":
                q = parse_qs(url.query)
                try:
                    new = r.shard_map.move_range(
                        int(q["lo"][0]), int(q["hi"][0]),
                        int(q["group"][0]))
                    r.install_map(new)
                except (KeyError, ValueError, IndexError) as e:
                    return _response(400, b"", {"Err": repr(e)})
                return _response(
                    200, json.dumps(new.to_json()).encode(),
                    {"Content-Type": "application/json"})
            return _response(404)
        if parts and parts[0] == "metrics":
            if method != "GET":
                return _response(405, b"", {"Err": "GET only"})
            snap = await r.metrics_snapshot()
            if parse_qs(url.query).get("format", [""])[0] == "json":
                return _response(200, json.dumps(snap).encode(),
                                 {"Content-Type": "application/json"})
            return _response(
                200, render_prometheus(snap).encode(),
                {"Content-Type":
                 "text/plain; version=0.0.4; charset=utf-8"})
        if parts and parts[0] == "spans":
            # one stitched scrape: router roots + coordinator records
            # + every group's node spans, group-labeled (obs/stitch.py)
            if method != "GET":
                return _response(405, b"", {"Err": "GET only"})
            spans = await r.spans_snapshot()
            if parse_qs(url.query).get("clear", [""])[0] in ("1",
                                                             "true"):
                r.spans.clear()
            return _response(
                200, json.dumps({"node": "router",
                                 "spans": spans}).encode(),
                {"Content-Type": "application/json"})
        return _response(404)
