"""Live data migration & elastic resharding (the wpaxos steal at
shard-range granularity).

PR 13's ``move_range`` is control-plane only: the map flips, but a
moved range arrives empty at its new owner.  This module makes
resharding a first-class ONLINE operation — a range moves with its
data, under load, without losing a write or serving a stale read from
the wrong side of the handoff.  The protocol is wpaxos phase-1 key
stealing lifted from per-object to range granularity: every state
transition of the handoff is one opaque record (core/command.pack_mig)
committed in a group's OWN Paxos log, so crash recovery at any point
is just replaying the log — the epoch state machine lives in
``Database._execute_mig``, and the coordinator here is a stateless
driver that can die and re-run.

Epochs (``MigrationCoordinator.move_range``), for ``[lo, hi)`` moving
``src -> dst``:

1. **snapshot** — ``begin``@dst opens the install window (and dirty
   tracking: any key the window sees written after ``begin`` is
   *dirty*, and later ``install``s skip it, so a streamed item can
   never clobber a newer duplicated write).  Then the bulk stream:
   ``read``@src pages committed range state out of src's log in key
   order, ``install``@dst commits each chunk into dst's log.
2. **double-write** — the map gains a migration entry
   (``ShardMap.with_migration``, version + 1) and is installed on
   every holder/router: writes in the range now ship to BOTH groups
   (router.py's dual-write fence), reads still come from src.  After
   a per-router ``barrier(src)`` (all previously accepted writes are
   on src's wire), ``start``@src commits the fence: it log-orders
   after every pre-fence write AND freezes new 2PC prepares on the
   range, so the catch-up stream that follows it observes everything
   the bulk stream raced with.
3. **cutover** — ``complete_migration`` (version + 2, dst owns) is
   installed on the holders FIRST, then ``cutover``@src releases the
   range — busy-retried while any in-doubt 2PC stage intersects it
   (releasing earlier could strand that transaction's commit).  From
   here src answers the range with the MOVED marker and stale routers
   bounce + refresh (router.py ``_rebounce``).
4. **drain** — a final catch-up stream picks up freeze-window 2PC
   commits of pre-fence-staged transactions (src's range is immutable
   post-cutover, so this stream is complete by construction), then
   ``done``@dst closes the window and ``drop``@src deletes the moved
   keys.  The released marker persists so laggards keep bouncing.

Recovery is re-running ``move_range`` with the same arguments: every
record is idempotent, ``begin`` answers ``done`` for a finished
migration, a map that already carries the migration entry resumes at
double-write, and a map that already routes the range to ``dst``
resumes at drain.  Known limits (documented, tested as such): a
repeat migration of the SAME (lo, hi, dst) triple needs an explicit
fresh ``mid``; negative keys are missed by the cursor-paged stream
(the KV surfaces in this repo use non-negative keys); and a
post-cutover crash resumed without ``src`` skips the final ``drop``
(the old owner leaks the moved keys until a manual drain).

The **Rebalancer** is the elastic policy plane: off the router's
per-group routed-command counters and its 64-bucket key histogram it
decides — with hysteresis (``min_ticks`` consecutive observations, a
``cooldown`` after every action) — to split a hot range at its load
median onto the least-loaded group, or merge a cold group's range
into its neighbor.  ``tick`` is pure (explicit inputs, a plan dict or
None out) so tests drive it deterministically; ``step`` wires it to a
live router + coordinator.

``MapHolder`` is the minimal fenced holder of the versioned map for
coordinator deployments without a router in-process (fabric tests,
CLI tools): the same lock/snapshot/version-guarded-swap discipline as
``ShardRouter`` — this file is part of the PXE15x proof surface
(analysis/epochfence.py), and stays at zero baseline entries.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict, List, Optional, Sequence

from paxi_tpu.shard.shardmap import ShardMap

_BUCKETS = 64       # must match ShardRouter._bucket_hits


class MigrationError(Exception):
    """A handoff step failed in a way re-running cannot mask (bad
    arguments, transport failure, a starved cutover)."""


class MigrationKilled(Exception):
    """Crash injection marker (the migration analog of
    txn.CoordinatorKilled): raised at the configured epoch so tests
    can kill the coordinator mid-protocol and assert that a re-run
    converges by log order."""


class MapHolder:
    """A fenced ``ShardMap`` holder for router-less deployments: the
    exact swap discipline the router documents — snapshot under the
    lock, install only under the lock behind a strict version-advance
    guard — so fabric tests and CLI tools share the PXE-proven shape
    instead of growing a third, unchecked map cache."""

    def __init__(self, shard_map: ShardMap):
        shard_map.validate()
        self._lock = threading.Lock()
        self._map = shard_map

    @property
    def shard_map(self) -> ShardMap:
        with self._lock:
            return self._map

    def install_map(self, new_map: ShardMap) -> None:
        new_map.validate()
        with self._lock:
            if new_map.version <= self._map.version:
                raise ValueError(
                    f"stale map: version {new_map.version} <= "
                    f"installed {self._map.version}")
            self._map = new_map


class MigrationCoordinator:
    """Drives one range handoff through its epochs.

    ``submit(group, key, rec)`` is the record transport (the 2PC
    coordinator's shape): commit one migration record dict in
    ``group``'s log and return ``(ok, reply_payload)`` — HTTP POST
    /mig in live deployments, direct leader injection in fabric
    tests.  ``holders`` are the map caches to keep in lockstep
    (ShardRouter and/or MapHolder instances; the FIRST is the
    authority whose map seeds each derivation); holders exposing a
    ``barrier(group)`` coroutine (routers) are fenced before the
    ``start`` record so the fence log-orders after every write they
    already accepted.
    """

    BUSY_TRIES = 200

    def __init__(self, submit, holders: Sequence, chunk: int = 64,
                 crash_at: Optional[str] = None,
                 busy_wait_s: float = 0.05):
        if not holders:
            raise ValueError("need at least one map holder")
        self._submit = submit
        self._holders = list(holders)
        self.chunk = int(chunk)
        # one-shot crash injection: "snapshot" (after the first bulk
        # chunk), "double_write" (fence committed, catch-up not run),
        # "cutover" (range released, drain not run)
        self.crash_at = crash_at
        self.busy_wait_s = busy_wait_s
        self.state: Dict = {}

    def status(self) -> Dict:
        return dict(self.state)

    # ---- the driver -----------------------------------------------------
    async def move_range(self, lo: int, hi: int, dst: int,
                         mid: Optional[str] = None,
                         src: Optional[int] = None) -> Dict:
        """Move ``[lo, hi)`` to group ``dst`` with its data; returns
        the final status dict.  Re-running with the same arguments
        resumes an interrupted handoff at the epoch the logs prove it
        reached."""
        m = self._holders[0].shard_map
        mid = mid or f"m{lo}-{hi}-{dst}"
        span = m.span
        mig = m.migration_of(lo)
        if mig is not None:
            if (mig[0], mig[1], mig[3]) != (lo, hi, dst):
                raise MigrationError(
                    f"range [{lo}, {hi}) overlaps in-flight "
                    f"migration {mig}")
            # the map already carries the window: a previous run got
            # past the double-write install — resume there (begin and
            # every later record are idempotent)
            self._begin_state(mid, lo, hi, mig[2], dst, "double-write")
            began = await self._begin(dst, mid, lo, hi, span)
            if began == b"done":
                raise MigrationError(
                    f"migration {mid} marked done at dst but the map "
                    f"still carries its window")
            return await self._double_write(mid, lo, hi, span,
                                            mig[2], dst)
        owner = m.group_of(lo)
        if owner == dst:
            # post-cutover resume (or an outright no-op): the map
            # already routes the range to dst — finish the drain
            self._begin_state(mid, lo, hi, src, dst, "drain")
            return await self._drain(mid, lo, hi, span, src, dst)
        src = owner
        if any(m.group_of(k) != src for k in m.starts if lo < k < hi):
            raise MigrationError(
                f"range [{lo}, {hi}) spans several owner groups")
        # ---- epoch 1: snapshot ----
        self._begin_state(mid, lo, hi, src, dst, "snapshot")
        began = await self._begin(dst, mid, lo, hi, span)
        if began == b"done":
            raise MigrationError(
                f"mid {mid} was already used for a completed "
                f"migration; pass a fresh explicit mid")
        await self._stream(mid, lo, hi, span, src, dst,
                           kill="snapshot")
        return await self._double_write(mid, lo, hi, span, src, dst)

    async def _double_write(self, mid: str, lo: int, hi: int,
                            span: int, src: int, dst: int) -> Dict:
        # ---- epoch 2: double-write ----
        self.state["epoch"] = "double-write"
        mp = self._holders[0].shard_map
        if mp.migration_of(lo) is None:
            m1 = mp.with_migration(lo, hi, dst)
            self._install_everywhere(m1)
        await self._barriers(src)
        await self._mig(src, lo, {"kind": "start", "mid": mid,
                                  "lo": lo, "hi": hi, "span": span})
        self._maybe_kill("double_write")
        await self._stream(mid, lo, hi, span, src, dst)
        # ---- epoch 3: cutover ----
        self.state["epoch"] = "cutover"
        mp = self._holders[0].shard_map
        if mp.migration_of(lo) is not None:
            m2 = mp.complete_migration(lo, hi)
            self._install_everywhere(m2)
        for _ in range(self.BUSY_TRIES):
            out = await self._mig(
                src, lo, {"kind": "cutover", "mid": mid, "lo": lo,
                          "hi": hi, "span": span})
            if out != b"busy":
                break
            # an in-doubt 2PC stage intersects the range: wait for
            # its coordinator (or recovery) to decide, then retry
            await asyncio.sleep(self.busy_wait_s)
        else:
            raise MigrationError(
                f"cutover of [{lo}, {hi}) starved by staged 2PC "
                f"transactions")
        self._maybe_kill("cutover")
        return await self._drain(mid, lo, hi, span, src, dst)

    async def _drain(self, mid: str, lo: int, hi: int, span: int,
                     src: Optional[int], dst: int) -> Dict:
        # ---- epoch 4: drain ----
        self.state["epoch"] = "drain"
        if src is not None:
            await self._stream(mid, lo, hi, span, src, dst)
        await self._mig(dst, lo, {"kind": "done", "mid": mid})
        if src is not None:
            await self._mig(src, lo, {"kind": "drop", "mid": mid,
                                      "lo": lo, "hi": hi,
                                      "span": span})
        self.state["epoch"] = "complete"
        return self.status()

    # ---- steps ----------------------------------------------------------
    def _begin_state(self, mid, lo, hi, src, dst, epoch) -> None:
        self.state = {"mid": mid, "lo": lo, "hi": hi, "src": src,
                      "dst": dst, "epoch": epoch, "chunks": 0,
                      "installed": 0}

    async def _begin(self, dst: int, mid: str, lo: int, hi: int,
                     span: int) -> bytes:
        return await self._mig(dst, lo, {"kind": "begin", "mid": mid,
                                         "lo": lo, "hi": hi,
                                         "span": span})

    async def _mig(self, group: int, key: int, rec: dict) -> bytes:
        ok, payload = await self._submit(group, key, rec)
        if not ok:
            raise MigrationError(
                f"{rec['kind']}@group{group} failed: {payload!r}")
        return payload

    async def _stream(self, mid: str, lo: int, hi: int, span: int,
                      src: int, dst: int,
                      kill: Optional[str] = None) -> int:
        """One read/install pass over the range: pages src's
        committed state in key order and commits each chunk into
        dst's log; ``install`` skips keys dst saw written since
        ``begin``, so any pass after the first only fills gaps."""
        cursor, total = -1, 0
        while True:
            payload = await self._mig(
                src, lo, {"kind": "read", "mid": mid, "lo": lo,
                          "hi": hi, "span": span, "cursor": cursor,
                          "limit": self.chunk})
            if not payload.startswith(b"items:"):
                raise MigrationError(
                    f"bad read reply from group {src}: {payload!r}")
            doc = json.loads(payload[len(b"items:"):].decode())
            items = [(int(k), v.encode("latin1"))
                     for k, v in doc["items"]]
            if items:
                await self._mig(
                    dst, lo, {"kind": "install", "mid": mid,
                              "lo": lo, "hi": hi, "span": span,
                              "items": items})
                total += len(items)
            self.state["chunks"] += 1
            self.state["installed"] += len(items)
            if kill is not None:
                self._maybe_kill(kill)
            if doc["next"] < 0:
                return total
            cursor = doc["next"]

    def _install_everywhere(self, new_map: ShardMap) -> None:
        for h in self._holders:
            try:
                h.install_map(new_map)
            except ValueError:
                pass   # that holder already saw this (or a newer) map

    async def _barriers(self, group: int) -> None:
        for h in self._holders:
            b = getattr(h, "barrier", None)
            if b is not None:
                await b(group)

    def _maybe_kill(self, point: str) -> None:
        if self.crash_at == point:
            self.crash_at = None   # one-shot, so a re-run completes
            raise MigrationKilled(f"killed at {point} "
                                  f"({self.state.get('mid')})")


class Rebalancer:
    """Load-driven auto-split/merge with hysteresis.

    Per tick the caller hands in the current (fenced) map, the
    per-group routed-command counts SINCE THE LAST TICK, and the
    router's 64-bucket key-histogram deltas.  A group holding at
    least ``hot_share`` of the tick's commands for ``min_ticks``
    consecutive ticks triggers a **split** plan: its hottest range is
    cut at the load median (the bucket boundary that halves the
    range's hits) and the upper half is assigned to the least-loaded
    group.  A group at or under ``cold_share`` for ``min_ticks``
    ticks triggers a **merge** plan: its first range folds into the
    neighboring owner.  After any plan, ``cooldown`` ticks pass
    before the next decision, and ticks with fewer than ``min_cmds``
    total commands reset the streaks — both guards against flapping
    on noise.

    ``tick`` is pure decision-making (a plan dict or None);
    ``step`` executes the loop against a live router + coordinator.
    """

    def __init__(self, hot_share: float = 0.5,
                 cold_share: float = 0.05, min_ticks: int = 3,
                 min_cmds: int = 50, cooldown: int = 3):
        self.hot_share = hot_share
        self.cold_share = cold_share
        self.min_ticks = min_ticks
        self.min_cmds = min_cmds
        self.cooldown = cooldown
        self._hot: Dict[int, int] = {}
        self._cold: Dict[int, int] = {}
        self._quiet = 0
        self._last_cmds: Optional[List[float]] = None

    def tick(self, shard_map: ShardMap, group_cmds: Sequence[float],
             bucket_hits: Sequence[int]) -> Optional[Dict]:
        total = sum(group_cmds)
        if self._quiet > 0:
            self._quiet -= 1
            return None
        if total < self.min_cmds:
            self._hot.clear()
            self._cold.clear()
            return None
        for g, c in enumerate(group_cmds):
            share = c / total
            self._hot[g] = self._hot.get(g, 0) + 1 \
                if share >= self.hot_share else 0
            self._cold[g] = self._cold.get(g, 0) + 1 \
                if share <= self.cold_share else 0
        hot = max(range(len(group_cmds)),
                  key=lambda g: group_cmds[g])
        if self._hot.get(hot, 0) >= self.min_ticks:
            plan = self._split_plan(shard_map, hot, group_cmds,
                                    bucket_hits)
            if plan is not None:
                return self._emit(plan)
        cold = min(range(len(group_cmds)),
                   key=lambda g: group_cmds[g])
        if self._cold.get(cold, 0) >= self.min_ticks \
                and len(set(shard_map.groups)) > 1:
            plan = self._merge_plan(shard_map, cold)
            if plan is not None:
                return self._emit(plan)
        return None

    def _emit(self, plan: Dict) -> Dict:
        self._hot.clear()
        self._cold.clear()
        self._quiet = self.cooldown
        return plan

    def _split_plan(self, m: ShardMap, hot: int,
                    group_cmds: Sequence[float],
                    bucket_hits: Sequence[int]) -> Optional[Dict]:
        others = [g for g in range(len(group_cmds)) if g != hot]
        if not others:
            return None
        dst = min(others, key=lambda g: group_cmds[g])
        ranges = m.ranges_of(hot)
        if not ranges:
            return None
        best = max(ranges,
                   key=lambda r: self._range_hits(m.span,
                                                  bucket_hits, *r))
        lo, hi = best
        at = self._median_cut(m.span, bucket_hits, lo, hi)
        if at is None:
            return None
        return {"action": "split", "lo": at, "hi": hi, "src": hot,
                "dst": dst}

    def _merge_plan(self, m: ShardMap, cold: int) -> Optional[Dict]:
        ranges = m.ranges_of(cold)
        if not ranges:
            return None
        lo, hi = ranges[0]
        # fold into the neighboring owner: the range just below, or
        # just above when the cold range starts the span
        probe = lo - 1 if lo > 0 else hi
        dst = m.group_of(probe)
        if dst == cold:
            return None
        return {"action": "merge", "lo": lo, "hi": hi, "src": cold,
                "dst": dst}

    @staticmethod
    def _range_hits(span: int, hits: Sequence[int], lo: int,
                    hi: int) -> int:
        total = 0
        for b, h in enumerate(hits):
            mid = (b * span + span // 2) // _BUCKETS
            if lo <= mid < hi:
                total += h
        return total

    @staticmethod
    def _median_cut(span: int, hits: Sequence[int], lo: int,
                    hi: int) -> Optional[int]:
        """The bucket boundary strictly inside (lo, hi) closest to
        halving the range's hits; the arithmetic midpoint when the
        histogram is too coarse to cut (all hits in one bucket)."""
        inside = []
        for b in range(len(hits)):
            edge = (b * span) // _BUCKETS
            if lo < edge < hi:
                inside.append((edge, b))
        if not inside:
            return (lo + hi) // 2 if hi - lo > 1 else None
        def mass_below(edge):
            return sum(h for b, h in enumerate(hits)
                       if lo <= (b * span + span // 2) // _BUCKETS
                       < edge)
        half = Rebalancer._range_hits(span, hits, lo, hi) / 2
        if half <= 0:
            return None
        return min((e for e, _ in inside),
                   key=lambda e: abs(mass_below(e) - half))

    async def step(self, router, coordinator) -> Optional[Dict]:
        """One live iteration: read the router's evidence (command
        deltas + histogram), decide, and when a plan comes out run
        the streamed move for it.  Returns the executed plan."""
        cmds = [c.value for c in router._group_fwd]
        if self._last_cmds is None:
            self._last_cmds = cmds
            return None
        deltas = [c - p for c, p in zip(cmds, self._last_cmds)]
        self._last_cmds = cmds
        hits = router.bucket_hits(reset=True)
        plan = self.tick(router.shard_map, deltas, hits)
        if plan is None:
            return None
        await coordinator.move_range(plan["lo"], plan["hi"],
                                     plan["dst"])
        return plan
