"""Cluster-of-clusters: G independent consensus groups behind one
router endpoint.

Each group is an ordinary ``host.simulation.Cluster`` — its own
config, its own chan fabric tag, any registered protocol — with the
group index folded into the zone digit of every replica id
(group g's replicas are ``{g+1}.1 .. {g+1}.n``), so the ids stay
globally unique and a SINGLE virtual-clock fabric can sequence all
groups in one logical clock (the fabric-replayed 2PC tests ride
this).  HTTP ports stack per group off one base port.

``proc=True`` runs each group as a ``server -simulation`` subprocess
instead (chan peers inside the subprocess, real TCP HTTP towards the
router) — the honest topology for throughput measurements: the groups
stop sharing the router/generator interpreter.

``routers=N`` starts N router endpoints over the same groups: one
PRIMARY (``router_url``) that owns map changes, plus N-1 stateless
secondaries (``router_urls``) that converge on a new map lazily — via
the coordinator's ``install_map`` fan-out when in its holder list, or
via the MOVED-bounce ``_map_refresh`` hook (GET /shardmap off the
primary) when a backend tells them their map is stale.  That is the
scale-out story for the router bottleneck BENCH_SHARD.json measures
past G=2: routers share nothing but the versioned map.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Sequence, Union

from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.shard.router import RouterServer, ShardRouter
from paxi_tpu.shard.shardmap import ShardMap


def group_config(g: int, n: int, base_port: int, tag: str = "shard",
                 http: bool = True, batch_size: int = 64,
                 lease_s: float = 0.2) -> Config:
    """Group g's config: zone digit g+1, chan tag ``{tag}{g}``, HTTP
    ports ``base_port + g*n ..``."""
    cfg = Config()
    cfg.batch_size = batch_size
    cfg.lease_s = lease_s
    for k in range(1, n + 1):
        i = ID(f"{g + 1}.{k}")
        cfg.addrs[i] = f"chan://{tag}{g}/{i}"
        if http:
            cfg.http_addrs[i] = \
                f"http://127.0.0.1:{base_port + g * n + (k - 1)}"
    return cfg


class ShardedCluster:
    """G groups + the shard router, one start/stop lifecycle.

    ``algorithm`` may be one name for every group or a per-group
    sequence (heterogeneous fleets are first-class: any registered
    host protocol per group)."""

    def __init__(self, algorithm: Union[str, Sequence[str]],
                 groups: int = 2, n: int = 3,
                 shard_map: Optional[ShardMap] = None,
                 base_port: int = 0, router_port: int = 0,
                 http: bool = True, fabric=None, proc: bool = False,
                 tag: str = "shard", batch_size: int = 64,
                 lease_s: float = 0.2, routers: int = 1):
        if isinstance(algorithm, str):
            algorithm = [algorithm] * groups
        if len(algorithm) != groups:
            raise ValueError(f"{len(algorithm)} algorithms for "
                             f"{groups} groups")
        self.algorithms = list(algorithm)
        self.G = groups
        self.n = n
        self.map = shard_map or ShardMap.static(groups)
        if self.map.n_groups > groups:
            raise ValueError(f"map names group {self.map.n_groups - 1} "
                             f"but the fleet has {groups} groups")
        self.proc = proc
        self.fabric = fabric
        self.http = http or proc
        self.base_port = base_port or 18300
        self.router_port = router_port or (self.base_port + 99)
        self.cfgs = [group_config(g, n, self.base_port, tag=tag,
                                  http=self.http, batch_size=batch_size,
                                  lease_s=lease_s)
                     for g in range(groups)]
        self.n_routers = max(1, routers)
        self.clusters: List = []        # in-proc mode
        self.procs: List[subprocess.Popen] = []
        self._cfg_paths: List[str] = []
        self.router: Optional[ShardRouter] = None
        self.server: Optional[RouterServer] = None
        # (router, server) pairs for the stateless secondary tier
        self.secondaries: List = []
        self._mig_conns: Dict[int, object] = {}

    # ---- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        from paxi_tpu.host.simulation import Cluster
        if self.proc:
            for g, cfg in enumerate(self.cfgs):
                with tempfile.NamedTemporaryFile(
                        "w", suffix=f".shard{g}.json",
                        delete=False) as f:
                    path = f.name
                cfg.to_json(path)
                self._cfg_paths.append(path)
                self.procs.append(subprocess.Popen(
                    [sys.executable, "-m", "paxi_tpu", "server",
                     "-simulation", "-algorithm", self.algorithms[g],
                     "-config", path],
                    env={**os.environ, "JAX_PLATFORMS": "cpu"}))
            from paxi_tpu.host.transport import wait_listening
            for cfg in self.cfgs:
                if not await wait_listening(cfg.http_addrs[cfg.ids[0]]):
                    raise RuntimeError("shard group subprocess never "
                                       "came up")
        else:
            self.clusters = [
                Cluster(self.algorithms[g], cfg=cfg, http=self.http,
                        fabric=self.fabric)
                for g, cfg in enumerate(self.cfgs)]
            for c in self.clusters:
                await c.start()
        if self.http:
            urls = [cfg.http_addrs[cfg.ids[0]] for cfg in self.cfgs]
            self.router = ShardRouter(
                self.map, urls,
                lease_s=self.cfgs[0].lease_s,
                group_scrape=self._scrape_groups,
                group_scrape_spans=self._scrape_spans)
            self.server = RouterServer(
                self.router, f"http://127.0.0.1:{self.router_port}")
            await self.server.start()
            for k in range(1, self.n_routers):
                r = ShardRouter(self.map, urls,
                                lease_s=self.cfgs[0].lease_s)
                r._map_refresh = self._refresh_for(r)
                s = RouterServer(
                    r, f"http://127.0.0.1:{self.router_port + k}")
                await s.start()
                self.secondaries.append((r, s))

    def _refresh_for(self, r: ShardRouter):
        """A secondary router's map-refresh hook: pull the primary's
        current map and install it (a no-op ValueError when this
        router already caught up)."""
        async def refresh() -> None:
            from paxi_tpu.host.client import _Conn
            conn = _Conn(self.router_url)
            try:
                status, _, payload = await conn.request(
                    "GET", "/shardmap", {}, b"")
                if status == 200:
                    r.install_map(ShardMap.from_json(payload.decode()))
            finally:
                conn.close()
        return refresh

    async def stop(self) -> None:
        for _, s in self.secondaries:
            await s.stop()
        self.secondaries = []
        for conn in self._mig_conns.values():
            conn.close()
        self._mig_conns = {}
        if self.server:
            await self.server.stop()
        for c in self.clusters:
            await c.stop()
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        for path in self._cfg_paths:
            try:
                os.unlink(path)
            except OSError:
                pass
        self.procs, self._cfg_paths = [], []

    # ---- access ---------------------------------------------------------
    @property
    def router_url(self) -> str:
        return f"http://127.0.0.1:{self.router_port}"

    @property
    def router_urls(self) -> List[str]:
        """Every router endpoint: the primary first, then the
        stateless secondaries."""
        return [f"http://127.0.0.1:{self.router_port + k}"
                for k in range(self.n_routers)]

    # ---- live migration -------------------------------------------------
    def migrator(self, chunk: int = 64, crash_at: Optional[str] = None,
                 busy_wait_s: float = 0.05):
        """A MigrationCoordinator over this fleet: records travel as
        POST /mig to each group's entry node, and every router (the
        primary AND the secondaries) is in the holder list, so map
        epochs install everywhere before the records that depend on
        them commit."""
        from paxi_tpu.shard.migrate import MigrationCoordinator
        if self.router is None:
            raise RuntimeError("migrator() needs the HTTP router tier")
        holders = [self.router] + [r for r, _ in self.secondaries]
        return MigrationCoordinator(self._mig_submit, holders,
                                    chunk=chunk, crash_at=crash_at,
                                    busy_wait_s=busy_wait_s)

    async def _mig_submit(self, group: int, key: int, rec: dict):
        """Migration-record transport: POST /mig to the group's entry
        node over a dedicated per-group connection (records must not
        queue behind a KV burst in the router's shared pipes)."""
        from paxi_tpu.host.client import _Conn
        doc: Dict = {"kind": rec["kind"], "mid": rec["mid"],
                     "key": int(key)}
        for f in ("lo", "hi", "span", "cursor", "limit"):
            if f in rec:
                doc[f] = int(rec[f])
        if "items" in rec:
            doc["items"] = [[k, v.decode("latin1")]
                            for k, v in rec["items"]]
        conn = self._mig_conns.get(group)
        if conn is None:
            cfg = self.cfgs[group]
            conn = _Conn(cfg.http_addrs[cfg.ids[0]])
            self._mig_conns[group] = conn
        try:
            status, _, payload = await conn.request(
                "POST", "/mig", {}, json.dumps(doc).encode())
            return status == 200, payload
        except (IOError, OSError) as e:
            return False, repr(e).encode()

    def group(self, g: int):
        """The in-proc Cluster of group g (in-proc mode only)."""
        return self.clusters[g]

    def leader_node(self, g: int):
        """Group g's entry replica (in-proc mode only) — the node the
        router's pipes dial, and the direct-injection point for the
        fabric-replayed 2PC tests."""
        c = self.clusters[g]
        return c.replicas[c.cfg.ids[0]]

    async def _scrape_groups(self) -> List[List[Dict]]:
        """Per-group registry snapshots for the router's /metrics
        aggregation (``group`` label applied by the router)."""
        if self.clusters:
            return [[r.metrics.snapshot()
                     for r in c.replicas.values()]
                    for c in self.clusters]
        # subprocess mode: scrape each group's entry node
        from paxi_tpu.host.client import _Conn
        out: List[List[Dict]] = []
        for cfg in self.cfgs:
            conn = _Conn(cfg.http_addrs[cfg.ids[0]])
            try:
                status, _, payload = await conn.request(
                    "GET", "/metrics?format=json", {}, b"")
                out.append([json.loads(payload.decode())]
                           if status == 200 else [])
            except (IOError, OSError):
                out.append([])
            finally:
                conn.close()
        return out

    async def _scrape_spans(self) -> List[List[Dict]]:
        """Per-group span exports for the router's /spans stitching —
        the span twin of ``_scrape_groups`` (the router stamps the
        ``group`` label before merging)."""
        if self.clusters:
            return [[d for r in c.replicas.values()
                     for d in r.spans.export()]
                    for c in self.clusters]
        from paxi_tpu.host.client import _Conn
        out: List[List[Dict]] = []
        for cfg in self.cfgs:
            group: List[Dict] = []
            for i in cfg.ids:
                conn = _Conn(cfg.http_addrs[i])
                try:
                    status, _, payload = await conn.request(
                        "GET", "/spans", {}, b"")
                    if status == 200:
                        group.extend(
                            json.loads(payload.decode())["spans"])
                except (IOError, OSError):
                    pass
                finally:
                    conn.close()
            out.append(group)
        return out
