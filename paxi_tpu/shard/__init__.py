"""Sharded multi-group serving: key-range router, cluster-of-clusters,
cross-shard 2PC, live range migration (see README "Sharded serving")."""

from paxi_tpu.shard.cluster import ShardedCluster, group_config
from paxi_tpu.shard.migrate import (MapHolder, MigrationCoordinator,
                                    MigrationError, MigrationKilled,
                                    Rebalancer)
from paxi_tpu.shard.router import RouterServer, ShardRouter, label_group
from paxi_tpu.shard.shardmap import ShardMap
from paxi_tpu.shard.txn import (CoordinatorKilled, ShardCoordinator,
                                TxnOutcome, atomic_check, partition_ops)

__all__ = [
    "ShardMap", "ShardRouter", "RouterServer", "label_group",
    "ShardedCluster", "group_config", "ShardCoordinator",
    "CoordinatorKilled", "TxnOutcome", "partition_ops", "atomic_check",
    "MigrationCoordinator", "MigrationError", "MigrationKilled",
    "MapHolder", "Rebalancer",
]
