"""Cross-shard transactions: 2PC with per-group Paxos as the
participant log.

A transaction whose ops span several consensus groups cannot ride one
group's log (the single-command Transaction surface only totally
orders within a group).  This coordinator runs classic presumed-abort
two-phase commit where EVERY durable 2PC state transition is an
ordered command in some group's log (core/command.pack_tpc records,
interpreted by ``Database._execute_tpc``):

1. **prepare** fan-out — one prepare record per participant group,
   carrying that group's ops.  The record replicates through the
   group's batch-per-slot pipeline like any client write; its
   execution stages the ops and votes (NO on a staged-key conflict
   with another in-flight txn).
2. **decide** — the commit/abort decision is made durable as a decide
   record in the txn's HOME group (lowest participating group id).
   ``Database`` applies the FIRST decide record for a txid and replies
   with the winner, so the decision point is one totally-ordered log
   entry: whoever's decide record sorts first in the home log — the
   live coordinator's or a recovery's — IS the outcome, and the loser
   learns it from its own record's reply.
3. **commit/abort** fan-out — participants apply or drop their stage.

**Coordinator recovery** (the mid-2PC kill path): a recovering party
first waits out ``lease_s`` — the same leader-lease bound that fences
``cfg.leader_reads`` (a live coordinator whose decide is in flight
reaches its home leader within the lease envelope) — then writes
``decide(abort)`` to the home group.  First-wins turns the race into
log order: if the dead coordinator's decide(commit) landed, recovery's
abort LOSES and recovery completes the commit fan-out; otherwise abort
wins and recovery aborts the stragglers.  Either way every group
converges on one outcome — the atomicity the fabric-replayed
coordinator-kill test pins (tests/test_shard_txn.py).

Scope note: staged 2PC state rides each replica's ordered log AND the
P1b auxiliary snapshot (``Database.aux_snapshot`` / ``restore_aux``,
carried in the paxos P1b seam) — a leader elected across a frontier
jump restores in-doubt stages, decides, and migration windows from
the ahead acker instead of dropping them, so an election between
prepare and decide no longer loses staged ops (the fabric-replayed
election regression in tests/test_shard_txn.py pins this); elections
without frontier jumps still re-propose the records like any
uncommitted slot.

The coordinator is transport-agnostic: ``submit(group, key, record)``
— ``record`` a plain ``{"kind", "txid", "ops"?, "outcome"?}`` dict —
returns an awaitable resolving to ``(ok, payload)``; each transport
encodes the record ONCE in its own wire form.  The shard router backs
it with POST /tpc over dedicated group connections; the fabric tests
back it with ``pack_tpc`` + direct ``handle_client_request``
injection.
"""

from __future__ import annotations

import asyncio
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paxi_tpu.core.command import unpack_values

_txn_counter = itertools.count(1)

# ops for one group: [(key, value)] — empty value = read
GroupOps = List[Tuple[int, bytes]]


class CoordinatorKilled(Exception):
    """Test hook: the coordinator 'crashed' at a scripted 2PC point
    (hunt/cases.py SHARD_ROUTER_CASES); carries what the recovery
    needs to take over."""

    def __init__(self, txid: str, parts: Dict[int, GroupOps],
                 point: str):
        super().__init__(f"coordinator killed {point} ({txid})")
        self.txid = txid
        self.parts = parts
        self.point = point


@dataclass
class TxnOutcome:
    txid: str
    committed: bool
    # per-group prepare-point previous values, in each group's op
    # order (only meaningful on commit)
    values: Dict[int, List[bytes]] = field(default_factory=dict)
    err: str = ""


class ShardCoordinator:
    """Drives 2PC rounds over an injected submit transport."""

    # outcome fan-out retries before giving up on a participant (the
    # decide record is already durable by then, so a straggler is an
    # availability problem recover() can finish, never an atomicity one)
    FINISH_RETRIES = 3

    def __init__(self, submit, lease_s: float = 0.2,
                 metrics=None, tag: str = "c", spans=None):
        self._submit = submit
        self.lease_s = lease_s
        self._tag = tag
        # obs.SpanCollector (or None): a traced transaction opens one
        # span per 2PC record, and the record's child context rides
        # rec["trace"] to the participant — the cross-shard stitch
        self._spans = spans
        reg = metrics
        self._m = {
            k: (reg.counter(f"paxi_tpc_{k}_total") if reg is not None
                else None)
            for k in ("txns", "committed", "aborted", "recovered",
                      "fanout_incomplete")}

    def _count(self, k: str) -> None:
        c = self._m[k]
        if c is not None:
            c.inc()

    def new_txid(self) -> str:
        return f"2pc-{self._tag}-{next(_txn_counter)}"

    @staticmethod
    def home_of(parts: Dict[int, GroupOps]) -> int:
        return min(parts)

    async def _record(self, group: int, key: int, kind: str, txid: str,
                      ops: Optional[GroupOps] = None,
                      outcome: str = "",
                      trace=None) -> Tuple[bool, bytes]:
        rec: dict = {"kind": kind, "txid": txid}
        if ops is not None:
            rec["ops"] = ops
        if outcome:
            rec["outcome"] = outcome
        sp = None
        if self._spans is not None and trace is not None:
            sp = self._spans.start(kind, trace, group=str(group),
                                   txid=txid)
            if sp is not None:
                rec["trace"] = sp.child().encode()
        try:
            return await self._submit(group, key, rec)
        finally:
            if self._spans is not None:
                self._spans.finish(sp)

    async def run_txn(self, parts: Dict[int, GroupOps],
                      txid: Optional[str] = None,
                      crash_at: Optional[str] = None,
                      trace=None) -> TxnOutcome:
        """One 2PC round over ``parts`` (group -> its ops).

        ``crash_at`` (tests only): ``"mid_prepare"`` dies with only
        the home group's prepare sent, ``"after_prepare"`` after all
        prepares, ``"after_decide"`` after the decide record,
        ``"mid_commit"`` after the home group's outcome record."""
        if not parts:
            return TxnOutcome("", False, err="empty transaction")
        txid = txid or self.new_txid()
        self._count("txns")
        home = self.home_of(parts)
        groups = sorted(parts)
        if crash_at == "mid_prepare":
            await self._record(home, parts[home][0][0], "prepare",
                               txid, ops=parts[home], trace=trace)
            raise CoordinatorKilled(txid, parts, crash_at)
        votes = await asyncio.gather(*[
            self._record(g, parts[g][0][0], "prepare", txid,
                         ops=parts[g], trace=trace) for g in groups])
        yes = all(ok and payload.startswith(b"yes:")
                  for ok, payload in votes)
        if crash_at == "after_prepare":
            raise CoordinatorKilled(txid, parts, crash_at)
        outcome = await self._decide(parts, txid, "c" if yes else "a",
                                     trace=trace)
        if crash_at == "after_decide":
            raise CoordinatorKilled(txid, parts, crash_at)
        stragglers = await self._finish(parts, txid, outcome,
                                        crash_at=crash_at, trace=trace)
        if outcome != "c":
            self._count("aborted")
            return TxnOutcome(txid, False, err="aborted (conflict)"
                              if not yes else "aborted (decided)")
        self._count("committed")
        values = {g: unpack_values(votes[i][1][len(b"yes:"):])
                  for i, g in enumerate(groups)}
        # the decide record made the outcome durable, so the txn IS
        # committed even if a participant's outcome record could not
        # be delivered — surface the gap (a recover() pass or the
        # group's own log healing finishes it) instead of hiding it
        err = (f"commit fan-out incomplete: groups {stragglers} "
               f"unreachable (recover() completes them)"
               if stragglers else "")
        return TxnOutcome(txid, True, values=values, err=err)

    async def _decide(self, parts: Dict[int, GroupOps], txid: str,
                      want: str, trace=None) -> str:
        """Write the decide record to the home group; the reply is the
        WINNING outcome (first decide in the home log wins)."""
        home = self.home_of(parts)
        ok, payload = await self._record(home, parts[home][0][0],
                                         "decide", txid, outcome=want,
                                         trace=trace)
        if not ok:
            raise IOError(f"2pc decide({txid}) unreachable: "
                          f"{payload!r}")
        return payload.decode() or "a"

    async def _finish(self, parts: Dict[int, GroupOps], txid: str,
                      outcome: str,
                      crash_at: Optional[str] = None,
                      trace=None) -> List[int]:
        """Fan the outcome record to every participant, retrying each
        failed delivery ``FINISH_RETRIES`` times.  Returns the groups
        still unreached (counted; the caller reports them — the
        outcome itself is already durable in the home log)."""
        kind = "commit" if outcome == "c" else "abort"
        home = self.home_of(parts)
        if crash_at == "mid_commit":
            await self._record(home, parts[home][0][0], kind, txid,
                               trace=trace)
            raise CoordinatorKilled(txid, parts, crash_at)
        left = sorted(parts)
        for _ in range(1 + self.FINISH_RETRIES):
            if not left:
                break
            results = await asyncio.gather(*[
                self._record(g, parts[g][0][0], kind, txid, trace=trace)
                for g in left])
            left = [g for g, (ok, _) in zip(left, results) if not ok]
        if left:
            self._count("fanout_incomplete")
        return left

    async def recover(self, txid: str,
                      parts: Dict[int, GroupOps],
                      trace=None) -> str:
        """Take over an in-doubt txn after a coordinator death: fence
        out the (possibly still live) coordinator's decide window,
        force a decide(abort) — first-wins reports the truth — and
        drive every participant to the winning outcome.  Returns the
        outcome ("c"/"a")."""
        fence = self.lease_s
        if fence > 0:
            await asyncio.sleep(fence)
        outcome = await self._decide(parts, txid, "a", trace=trace)
        await self._finish(parts, txid, outcome, trace=trace)
        self._count("recovered")
        self._count("committed" if outcome == "c" else "aborted")
        return outcome


def partition_ops(shard_map, ops: List[Tuple[int, bytes]]
                  ) -> Dict[int, GroupOps]:
    """Split a transaction's ops by owning group under one map
    snapshot, preserving each group's op order."""
    parts: Dict[int, GroupOps] = {}
    for k, v in ops:
        parts.setdefault(shard_map.group_of(k), []).append((int(k), v))
    return parts


def atomic_check(reads_by_group: Dict[int, List[Tuple[bytes, bytes]]]
                 ) -> bool:
    """The 2PC atomicity oracle: given each group's (expected txn
    value, observed value) pairs for one txid, every op observed the
    txn's write or none did."""
    applied = [obs == want
               for pairs in reads_by_group.values()
               for want, obs in pairs]
    return all(applied) or not any(applied)
