"""Host-side metrics model: counters + mergeable latency histograms.

Deliberately stdlib-only (the host runtime must not pull in jax) and
deliberately ONE fixed bucket layout for every histogram: log-spaced
bounds, 6 buckets per decade from 1 µs to 1000 s plus an overflow
bucket.  A shared layout is what makes merging exact — adding two
histograms' bucket-count vectors IS the histogram of the union of
their samples, so per-stream and per-node series aggregate without
approximation (the mergeability HdrHistogram/Prometheus lean on).

Percentiles are derived from buckets by nearest rank: the answer is
the geometric midpoint of the bucket holding the rank, i.e. exact to
within one bucket's width (~±21% at 6 buckets/decade) — the right
trade for an instrument whose job is spotting multi-x tail blowups,
not re-deriving the raw list.
"""

from __future__ import annotations

import bisect
import math
from typing import Any, Dict, Iterable, List, Optional, Tuple

# 6 log-spaced buckets per decade, 1 µs .. 1000 s (54 bounds), plus a
# +Inf overflow bucket.  Changing this breaks snapshot mergeability —
# from_snapshot()/merge_snapshots() check the stamped scheme version.
HIST_SCHEME = "log6:1e-6:54"
HIST_BOUNDS: Tuple[float, ...] = tuple(
    1e-6 * 10.0 ** ((i + 1) / 6.0) for i in range(54))
_N = len(HIST_BOUNDS)


class Counter:
    """A monotonically increasing integer."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """A settable instantaneous level (queue depth, in-flight count).

    Merge semantics across nodes is SUM: the fleet-level depth is the
    sum of per-node depths, the same way Prometheus users sum gauge
    series — a last-writer-wins merge would be meaningless for
    scrape-skewed snapshots."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n

    def dec(self, n: float = 1) -> None:
        self.value -= n


class Histogram:
    """Fixed-bucket log-spaced histogram; merge is exact (see module
    docstring).  Tracks exact sum/min/max alongside bucket counts."""

    __slots__ = ("counts", "count", "sum", "vmin", "vmax")

    def __init__(self) -> None:
        self.counts = [0] * (_N + 1)   # [..buckets.., overflow]
        self.count = 0
        self.sum = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    @property
    def min(self) -> float:
        return self.vmin if self.count else 0.0

    @property
    def max(self) -> float:
        return self.vmax if self.count else 0.0

    def observe(self, v: float) -> None:
        self.counts[min(bisect.bisect_left(HIST_BOUNDS, v), _N)] += 1
        self.count += 1
        self.sum += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def merge(self, other: "Histogram") -> "Histogram":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        return self

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile from buckets, clamped to the exact
        observed [min, max] envelope."""
        if not self.count:
            return 0.0
        rank = max(math.ceil(p / 100.0 * self.count), 1)
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                if i >= _N:             # overflow bucket
                    return self.vmax
                lo = HIST_BOUNDS[i - 1] if i else HIST_BOUNDS[0] / 10 ** (1 / 6)
                mid = math.sqrt(lo * HIST_BOUNDS[i])
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax

    # ---- snapshot (the JSON schema README documents) -------------------
    def to_snapshot(self) -> Dict[str, Any]:
        return {
            "scheme": HIST_SCHEME,
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            # sparse: bucket index -> count (index _N is overflow)
            "buckets": {str(i): c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "Histogram":
        if snap.get("scheme") != HIST_SCHEME:
            raise ValueError(
                f"histogram scheme {snap.get('scheme')!r} incompatible "
                f"with {HIST_SCHEME!r}")
        h = cls()
        for i, c in snap["buckets"].items():
            h.counts[int(i)] = int(c)
        h.count = int(snap["count"])
        h.sum = float(snap["sum"])
        if h.count:
            h.vmin = float(snap["min"])
            h.vmax = float(snap["max"])
        return h


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Registry:
    """Get-or-create store of labeled counters and histograms.

    ``Registry(node="1.1")`` stamps every exported series with the
    constant labels; per-series labels come from the call site
    (``reg.counter("paxi_msgs_in_total", type="P2a")``)."""

    def __init__(self, **labels: str) -> None:
        self.labels = {k: str(v) for k, v in labels.items()}
        self._counters: Dict[Tuple[str, tuple], Counter] = {}
        self._hists: Dict[Tuple[str, tuple], Histogram] = {}
        self._gauges: Dict[Tuple[str, tuple], Gauge] = {}

    def counter(self, name: str, **labels: str) -> Counter:
        key = (name, _label_key(labels))
        c = self._counters.get(key)
        if c is None:
            c = self._counters[key] = Counter()
        return c

    def gauge(self, name: str, **labels: str) -> Gauge:
        key = (name, _label_key(labels))
        g = self._gauges.get(key)
        if g is None:
            g = self._gauges[key] = Gauge()
        return g

    def histogram(self, name: str, **labels: str) -> Histogram:
        key = (name, _label_key(labels))
        h = self._hists.get(key)
        if h is None:
            h = self._hists[key] = Histogram()
        return h

    # ---- export --------------------------------------------------------
    def _full_labels(self, lk: tuple) -> Dict[str, str]:
        return {**self.labels, **dict(lk)}

    def snapshot(self) -> Dict[str, Any]:
        """The JSON form (``GET /metrics?format=json``)."""
        return {
            "counters": [
                {"name": n, "labels": self._full_labels(lk),
                 "value": c.value}
                for (n, lk), c in self._counters.items()],
            "gauges": [
                {"name": n, "labels": self._full_labels(lk),
                 "value": g.value}
                for (n, lk), g in self._gauges.items()],
            "histograms": [
                {"name": n, "labels": self._full_labels(lk),
                 **h.to_snapshot()}
                for (n, lk), h in self._hists.items()],
        }

    def prometheus(self) -> str:
        return render_prometheus(self.snapshot())


# ---- snapshot-level operations (merge / render / parse) -----------------
def merge_snapshots(snaps: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate snapshots: counters with identical (name, labels) add;
    histograms bucket-merge exactly (shared bounds)."""
    counters: Dict[Tuple[str, tuple], int] = {}
    gauges: Dict[Tuple[str, tuple], float] = {}
    hists: Dict[Tuple[str, tuple], Histogram] = {}
    labels: Dict[Tuple[str, tuple], Dict[str, str]] = {}
    for snap in snaps:
        for c in snap.get("counters", []):
            key = (c["name"], _label_key(c.get("labels", {})))
            counters[key] = counters.get(key, 0) + int(c["value"])
            labels[key] = dict(c.get("labels", {}))
        for g in snap.get("gauges", []):
            key = (g["name"], _label_key(g.get("labels", {})))
            gauges[key] = gauges.get(key, 0.0) + float(g["value"])
            labels[key] = dict(g.get("labels", {}))
        for hs in snap.get("histograms", []):
            key = (hs["name"], _label_key(hs.get("labels", {})))
            h = Histogram.from_snapshot(hs)
            if key in hists:
                hists[key].merge(h)
            else:
                hists[key] = h
            labels[key] = dict(hs.get("labels", {}))
    return {
        "counters": [{"name": n, "labels": labels[(n, lk)], "value": v}
                     for (n, lk), v in counters.items()],
        "gauges": [{"name": n, "labels": labels[(n, lk)], "value": v}
                   for (n, lk), v in gauges.items()],
        "histograms": [{"name": n, "labels": labels[(n, lk)],
                        **h.to_snapshot()}
                       for (n, lk), h in hists.items()],
    }


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render_prometheus(snap: Dict[str, Any]) -> str:
    """Prometheus text exposition (v0.0.4) of a snapshot."""
    out: List[str] = []
    seen_type: set = set()
    for c in snap.get("counters", []):
        if c["name"] not in seen_type:
            out.append(f"# TYPE {c['name']} counter")
            seen_type.add(c["name"])
        out.append(f"{c['name']}{_fmt_labels(c['labels'])} {c['value']}")
    for g in snap.get("gauges", []):
        if g["name"] not in seen_type:
            out.append(f"# TYPE {g['name']} gauge")
            seen_type.add(g["name"])
        out.append(f"{g['name']}{_fmt_labels(g['labels'])} "
                   f"{g['value']:.9g}")
    for hs in snap.get("histograms", []):
        name = hs["name"]
        if name not in seen_type:
            out.append(f"# TYPE {name} histogram")
            seen_type.add(name)
        labels = hs.get("labels", {})
        counts = [0] * (_N + 1)
        for i, c in hs["buckets"].items():
            counts[int(i)] = int(c)
        acc = 0
        for i, c in enumerate(counts[:_N]):
            acc += c
            if c:  # sparse text: only buckets that gained samples
                le = _fmt_labels({**labels, "le": f"{HIST_BOUNDS[i]:.3e}"})
                out.append(f"{name}_bucket{le} {acc}")
        le = _fmt_labels({**labels, "le": "+Inf"})
        out.append(f"{name}_bucket{le} {hs['count']}")
        out.append(f"{name}_sum{_fmt_labels(labels)} {hs['sum']:.9g}")
        out.append(f"{name}_count{_fmt_labels(labels)} {hs['count']}")
    return "\n".join(out) + ("\n" if out else "")


def parse_prometheus(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse exposition text back to (name, labels, value) samples —
    the scrape-side half the smoke test and the CLI lean on."""
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        if not head:
            continue
        labels: Dict[str, str] = {}
        name = head
        if head.endswith("}"):
            name, _, rest = head.partition("{")
            for part in rest[:-1].split(","):
                if not part:
                    continue
                k, _, v = part.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        samples.append((name, labels, float(val)))
    return samples


def pretty(snap: Dict[str, Any]) -> str:
    """Human-readable rendering of a snapshot (the CLI's output)."""
    lines: List[str] = []
    counters = sorted(snap.get("counters", []),
                      key=lambda c: (c["name"], sorted(c["labels"].items())))
    if counters:
        lines.append("counters:")
        width = max(len(c["name"] + _fmt_labels(c["labels"]))
                    for c in counters)
        for c in counters:
            tag = c["name"] + _fmt_labels(c["labels"])
            lines.append(f"  {tag:<{width}}  {c['value']}")
    gauges = sorted(snap.get("gauges", []),
                    key=lambda g: (g["name"], sorted(g["labels"].items())))
    if gauges:
        lines.append("gauges:")
        width = max(len(g["name"] + _fmt_labels(g["labels"]))
                    for g in gauges)
        for g in gauges:
            tag = g["name"] + _fmt_labels(g["labels"])
            lines.append(f"  {tag:<{width}}  {g['value']:g}")
    hists = sorted(snap.get("histograms", []),
                   key=lambda h: (h["name"], sorted(h["labels"].items())))
    if hists:
        lines.append("histograms:")
        for hs in hists:
            h = Histogram.from_snapshot(hs)
            tag = hs["name"] + _fmt_labels(hs["labels"])
            lines.append(
                f"  {tag}: count={h.count} mean={h.mean() * 1e3:.3f}ms "
                f"p50={h.percentile(50) * 1e3:.3f}ms "
                f"p95={h.percentile(95) * 1e3:.3f}ms "
                f"p99={h.percentile(99) * 1e3:.3f}ms "
                f"p999={h.percentile(99.9) * 1e3:.3f}ms "
                f"max={h.max * 1e3:.3f}ms")
    return "\n".join(lines) if lines else "(empty)"
