"""Sim-side metrics backend: on-device counters inside the scan body.

The sim runtime can't call a Python registry from inside a jitted
lock-step round, so its counters are integer reductions computed in
``runner._group_step`` and threaded out of the scan as per-step
outputs: every step contributes one int32 per counter (summed over the
whole group batch), ``runner.finish_run`` sums over time and folds the
totals into the run's metrics dict under the ``net_`` prefix, and
``parallel/mesh.py``'s psum adds them across shards like any other
metric.

Determinism contract: the counts are pure functions of (inbox, outbox,
fault planes, fault masks) — no extra PRNG draws — and the fault-plane
terms use the same effective-event predicate as the trace recorder
(``drop & valid & live``), so a pinned replay of an unedited capture
reports byte-identical counters.  Counter equality between capture and
replay is therefore a determinism check alongside the state hash.

Counters are flow-per-run (a resumed segment counts its own segment),
int32 like every other sim metric.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

NET_PREFIX = "net_"

# the fixed counter vocabulary (stripped names, as surfaced on
# SimResult.counters / trace meta / FUZZ_SOAK.json records)
COUNTER_NAMES = ("msgs_sent", "msgs_delivered", "msgs_dropped",
                 "msgs_duplicated", "msgs_delayed", "delay_collisions",
                 "crash_steps", "cut_edge_steps")


def step_counts(inbox, outbox, faults, fs, n: int, wheel=None
                ) -> Dict[str, jax.Array]:
    """One lock-step round's counter increments, summed over the whole
    batch (per-group under vmap — the caller sums the group axis).

    - ``msgs_sent``: protocol outbox emissions (pre-fault).
    - ``msgs_delivered``: wheel slots popped into this step's inbox.
    - ``msgs_dropped/duplicated/delayed``: EFFECTIVE fault events —
      masked by ``valid & live`` exactly like the trace recorder's
      neutralization, so schedule noise on empty edges never counts.
    - ``delay_collisions``: messages this step's ``wheel_insert`` will
      land on an already-occupied wheel cell, overwriting the earlier
      in-flight message on that (type, src, dst) edge — the sim's
      modeled-as-loss collision semantics (mailbox.py module docstring;
      the hunt engine's first real finding).  ``wheel`` is the
      post-delivery, pre-insert wheel; ``None`` (no wheel in scope)
      reports 0, keeping the counter total stable for fault-free runs.
    - ``crash_steps`` / ``cut_edge_steps``: fault-mask occupancy
      (replica-steps crashed, directed-edge-steps severed).
    """
    # function-local: sim.runner imports this module, so a top-level
    # sim.mailbox import would cycle through the sim package __init__
    from paxi_tpu.sim import mailbox as mb

    sample = next(iter(outbox.values()))["valid"]
    live = mb.live_mask(fs, sample.ndim, n)

    def tot(x):
        return jnp.sum(x, dtype=jnp.int32)

    sent = sum(tot(b["valid"]) for b in outbox.values())
    delivered = sum(tot(b["valid"]) for b in inbox.values())
    dropped = jnp.int32(0)
    duplicated = jnp.int32(0)
    delayed = jnp.int32(0)
    collisions = jnp.int32(0)
    for name in sorted(outbox.keys()):
        valid = outbox[name]["valid"] & live
        f = faults[name]
        dropped = dropped + tot(f["drop"] & valid)
        kept = valid & ~f["drop"]
        duplicated = duplicated + tot(f["dup"] & kept)
        delayed = delayed + tot((f["delay"] > 1) & kept)
        if wheel is not None and wheel[name]["valid"].shape[0] > 1:
            # mirror wheel_insert's slot targeting exactly: a put onto
            # a cell whose valid bit is already set is an overwrite.
            # A one-slot wheel (max_delay=1) is rotated empty before
            # every insert, so collisions are structurally impossible
            # there — skipped statically to keep fault-free runs free.
            d = wheel[name]["valid"].shape[0]
            dup_delay = jnp.minimum(f["delay"] + 1, d)
            for slot in range(d):
                put = kept & ((f["delay"] == slot + 1)
                              | (f["dup"] & (dup_delay == slot + 1)))
                collisions = collisions + tot(
                    put & wheel[name]["valid"][slot])
    return {
        NET_PREFIX + "msgs_sent": sent,
        NET_PREFIX + "msgs_delivered": delivered,
        NET_PREFIX + "msgs_dropped": dropped,
        NET_PREFIX + "msgs_duplicated": duplicated,
        NET_PREFIX + "msgs_delayed": delayed,
        NET_PREFIX + "delay_collisions": collisions,
        NET_PREFIX + "crash_steps": tot(fs["crashed"]),
        NET_PREFIX + "cut_edge_steps": tot(~fs["conn"]),
    }


def counters_of(metrics: Dict) -> Dict:
    """Strip the runner's counters out of a metrics dict (prefix
    removed) — the public ``SimResult.counters`` view."""
    return {k[len(NET_PREFIX):]: v for k, v in metrics.items()
            if k.startswith(NET_PREFIX)}
