"""Unified metrics layer: one model, two backends.

The source paper's whole method is *dissecting* replication-protocol
performance, and latency distributions / per-message-class counters —
not means — are what expose pathologies ("Performance of Paxos in the
Cloud", PAPERS.md).  This package gives both runtimes one metrics
vocabulary:

- **Host backend** (`registry.py`, stdlib-only — no jax import): a
  registry of labeled counters and fixed-bucket log-spaced latency
  histograms.  All histograms share ONE bucket layout, so merging is
  exact bucket-count addition — per-stream series merge into per-run
  series, per-node series merge into per-cluster series.  Exported as
  Prometheus text (`GET /metrics`) and a JSON snapshot
  (`GET /metrics?format=json`) from the node HTTP server.
- **Sim backend** (`simcount.py`): integer counter reductions threaded
  through the jitted scan body (delivered / dropped / duplicated /
  delayed messages, crash and partition mask occupancy), folded into
  the run's metrics dict under the ``net_`` prefix, summed across
  shards by `parallel/mesh.py`, and preserved bit-for-bit by trace
  capture/replay — counter equality between a recorded run and its
  pinned replay is a determinism check on top of the state hash.
"""

from paxi_tpu.metrics.registry import (HIST_BOUNDS, Counter, Gauge,
                                       Histogram, Registry,
                                       merge_snapshots, parse_prometheus,
                                       pretty, render_prometheus)

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "HIST_BOUNDS",
           "merge_snapshots", "parse_prometheus", "pretty",
           "render_prometheus"]
