"""Sim-side in-kernel commit-latency histograms.

The post-hoc latency accounting the zone-aware kernels pioneered
(PR 10's ``m_lat_*_sum/_n`` planes) reports *means*; the source papers'
point is that tails, not means, are what degrade first ("The
Performance of Paxos in the Cloud", PAPERS.md).  This module is the
distribution-shaped version: protocol kernels stamp each slot's FIRST
propose step into an ``m_prop_t`` plane, and on commit bin the
propose->commit step delta into a fixed log2-spaced int32 histogram
plane (``m_lat_hist``) *inside the scan body* — so a 100k-group bench
run reports p50/p99/p999 without ever materializing per-slot latencies
on host.

Layout: ``N_BUCKETS`` buckets over step deltas; bucket 0 holds
``dt <= 1``, bucket ``i`` (1..N_BUCKETS-2) holds ``dt`` in
``(2**(i-1), 2**i]``, the last bucket is overflow.  The layout is
FIXED so histogram planes merge by bucket-count addition — across
groups (the kernel's in-scan accumulate), across shards
(``parallel/mesh.py`` returns the plane inside the sharded state), and
across runs (plain vector adds).

Interop with the host layout: ``to_host_snapshot`` converts a sim
bucket vector into the host registry's snapshot schema
(``metrics/registry.py``, scheme ``log6:1e-6:54``) by mapping each sim
bucket's geometric-midpoint latency — at a caller-chosen
``step_seconds`` per lock-step round — onto the host bounds.  The
result bucket-merges exactly with host histograms and renders through
the registry's single ``pretty``/``percentile`` code path, which is
what lets ``python -m paxi_tpu metrics`` show sim and host
distributions side by side.

Like ``simcount.py``, the kernel-side helpers import jax; host-only
code should import ``paxi_tpu.metrics`` (registry only) instead.
All ``m_``-prefixed planes are excluded from the trace witness hash
(``trace/replay.state_hash``) and must never feed protocol logic —
enforced statically by the PXM10x rule family (analysis/measure.py).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

# bucket i (1..N-2) holds dt in (2**(i-1), 2**i] steps; bucket 0 is
# dt <= 1; the last bucket is overflow (dt > 2**(N-2)).  2**10 = 1024
# steps covers every sim horizon in the tree; longer runs land in the
# overflow bucket, which percentile() reports honestly as ">= bound".
N_BUCKETS = 12
BOUNDS_STEPS = tuple(2 ** i for i in range(N_BUCKETS - 1))  # 1..1024


def empty_hist(n_groups: Optional[int] = None):
    """Zeroed ``m_lat_hist`` plane: (N_BUCKETS, G) lane-major, or
    (N_BUCKETS,) for per-group kernels."""
    import jax.numpy as jnp
    shape = (N_BUCKETS,) if n_groups is None else (N_BUCKETS, n_groups)
    return jnp.zeros(shape, jnp.int32)


def hist_update(hist, dt, mask):
    """Accumulate masked step deltas into a histogram plane, in-scan.

    ``dt``/``mask`` share a shape whose trailing dims match
    ``hist[1:]`` (lane-major: trailing group axis; per-group: hist is
    (N_BUCKETS,) and everything reduces to scalars).  Implemented as
    one masked count per bucket BOUND (cumulative counts above each
    bound, then adjacent differences) — N_BUCKETS-1 fused
    compare+reduce passes, no (..., N_BUCKETS) one-hot intermediate.
    """
    import jax.numpy as jnp
    axes = tuple(range(dt.ndim - (hist.ndim - 1)))

    def tot(x):
        return jnp.sum(x, axis=axes, dtype=jnp.int32)

    above = [tot(mask & (dt > b)) for b in BOUNDS_STEPS]
    rows = [tot(mask) - above[0]]
    rows += [above[i] - above[i + 1] for i in range(len(above) - 1)]
    rows.append(above[-1])
    return hist + jnp.stack(rows)


def flush_every(n_slots: int) -> int:
    """Deferred-binning period for per-group kernels (see
    ``sim/runner`` ``flush_measurements``): a committed cell's pending
    delta must be binned before the ring can recycle the cell into a
    NEW commit, which takes at least ``n_slots`` frontier steps — so
    any period <= n_slots/2 is loss-free with margin."""
    return max(1, min(16, n_slots // 2))


def flush_pending(state):
    """Bin one group's pending ``m_commit_dt`` plane into its
    ``m_lat_hist`` and clear it (jnp; runs under the runner's
    every-K-steps ``lax.cond`` so the N_BUCKETS reduction fan costs
    1/K of a per-step implementation)."""
    import jax.numpy as jnp
    pend = state["m_commit_dt"]
    hist = hist_update(state["m_lat_hist"], pend, pend > 0)
    return dict(state, m_lat_hist=hist,
                m_commit_dt=jnp.zeros_like(pend))


# ---- host-side reductions (numpy; run after the scan) -------------------

def to_sparse(counts) -> Dict[str, int]:
    """Sparse ``{bucket_index: count}`` JSON form of a bucket vector —
    the ONE definition behind ``capture_lat_hist`` trace meta,
    ``ReplayResult.lat_hist`` and ``summarize()``'s buckets: capture
    and replay compare these byte-for-byte, so they must share the
    construction."""
    return {str(i): int(c)
            for i, c in enumerate(np.asarray(counts).reshape(-1)) if c}


def bin_steps(dts) -> np.ndarray:
    """Histogram a flat array of positive step deltas (numpy twin of
    ``hist_update``; used to fold an end-of-run pending plane)."""
    out = np.zeros(N_BUCKETS, np.int32)
    dts = np.asarray(dts).reshape(-1)
    dts = dts[dts > 0]
    if dts.size:
        idx = np.sum(dts[:, None] > np.asarray(BOUNDS_STEPS)[None, :],
                     axis=1)
        np.add.at(out, idx, 1)
    return out


def plane_total(plane) -> np.ndarray:
    """Sum a histogram plane (group-major final state: bucket axis
    LAST; or a single group's (N_BUCKETS,)) down to one bucket vector
    — the reduction behind ``total_hist`` and the per-key-class
    ``m_wl_hist_*`` planes (workload/compile.class_split)."""
    h = np.asarray(plane).astype(np.int64)
    return h.reshape(-1, N_BUCKETS).sum(axis=0).astype(np.int32)


def total_hist(state) -> Optional[np.ndarray]:
    """Whole-state commit-latency bucket vector: the accumulated
    ``m_lat_hist`` plane (group axis summed out) plus any samples
    still pending in ``m_commit_dt`` (committed after the last in-scan
    flush).  Works on the runner's group-major final state and on a
    single traced group's state; None when uninstrumented."""
    if not (isinstance(state, dict) and "m_lat_hist" in state):
        return None
    h = plane_total(state["m_lat_hist"])
    if "m_commit_dt" in state:
        h = h + bin_steps(state["m_commit_dt"])
    return h

def _midpoint_steps(i: int) -> float:
    """Geometric midpoint of bucket ``i`` in steps."""
    if i == 0:
        return 1.0
    if i >= N_BUCKETS - 1:                      # overflow
        return 2.0 * BOUNDS_STEPS[-1]
    return math.sqrt(BOUNDS_STEPS[i - 1] * BOUNDS_STEPS[i])


def percentile_steps(counts, p: float) -> float:
    """Nearest-rank percentile of a sim bucket vector, in steps (the
    same rule as ``registry.Histogram.percentile``, one bucket wide)."""
    counts = np.asarray(counts).reshape(-1)
    total = int(counts.sum())
    if not total:
        return 0.0
    rank = max(math.ceil(p / 100.0 * total), 1)
    acc = 0
    for i, c in enumerate(counts):
        acc += int(c)
        if acc >= rank:
            return _midpoint_steps(i)
    return _midpoint_steps(N_BUCKETS - 1)


def to_host_snapshot(counts, sum_steps: int,
                     step_seconds: float = 1.0) -> Dict[str, Any]:
    """Convert a sim bucket vector to the host registry's histogram
    snapshot schema (``registry.HIST_SCHEME``), at ``step_seconds``
    simulated seconds per lock-step round.

    Each sim bucket's count lands in the host bucket holding its
    geometric midpoint, so the conversion is exact bucket addition up
    to one (sim) bucket of quantization — the same envelope the host
    percentiles already carry.  The result merges with live host
    snapshots via ``registry.merge_snapshots`` and renders through the
    one registry code path (``pretty``/``Histogram.percentile``).
    min/max are bucket-bound envelopes (the kernel keeps no exact
    extrema), clamped to be mutually consistent for empty-adjacent
    layouts."""
    import bisect

    from paxi_tpu.metrics.registry import HIST_BOUNDS, HIST_SCHEME

    counts = np.asarray(counts).reshape(-1)
    assert counts.shape == (N_BUCKETS,), counts.shape
    n = len(HIST_BOUNDS)
    host = [0] * (n + 1)
    for i, c in enumerate(counts):
        if not c:
            continue
        v = _midpoint_steps(i) * step_seconds
        host[min(bisect.bisect_left(HIST_BOUNDS, v), n)] += int(c)
    total = int(counts.sum())
    nz = np.nonzero(counts)[0]
    vmin = vmax = 0.0
    if nz.size:
        lo = 0.0 if nz[0] == 0 else float(BOUNDS_STEPS[nz[0] - 1])
        hi = (float(BOUNDS_STEPS[nz[-1]]) if nz[-1] < N_BUCKETS - 1
              else 2.0 * BOUNDS_STEPS[-1])
        vmin, vmax = lo * step_seconds, hi * step_seconds
    return {
        "scheme": HIST_SCHEME,
        "count": total,
        "sum": float(sum_steps) * step_seconds,
        "min": vmin,
        "max": vmax,
        "buckets": {str(i): c for i, c in enumerate(host) if c},
    }


def summarize(counts, sum_steps: int) -> Dict[str, Any]:
    """The bench-row form: p50/p99/p999 in lock-step rounds plus the
    sample count and mean — small enough to embed per artifact row."""
    counts = np.asarray(counts).reshape(-1)
    total = int(counts.sum())
    return {
        "n": total,
        "mean_rounds": round(float(sum_steps) / total, 3) if total else 0.0,
        "p50_rounds": round(percentile_steps(counts, 50), 3),
        "p99_rounds": round(percentile_steps(counts, 99), 3),
        "p999_rounds": round(percentile_steps(counts, 99.9), 3),
        "buckets": to_sparse(counts),
    }
