"""Platform selection helper.

Some environments install a site customization that imports jax at
interpreter startup and overrides ``jax_platforms``; entry points call
:func:`ensure_env_platform` so the caller's ``JAX_PLATFORMS`` env var
(e.g. ``cpu`` with ``--xla_force_host_platform_device_count``) wins.
"""

import os


def ensure_env_platform() -> None:
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax
        jax.config.update("jax_platforms", want)
