"""Leveled logger.

Reference: paxi's ``log/`` package — a glog-style leveled logger
(``Debugf/Infof/Warningf/Errorf``) writing per-process files, configured
by ``-log_dir``, ``-log_level``, ``-log_stdout`` flags [med].  Thin
wrapper over stdlib logging with the same surface.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_logger = logging.getLogger("paxi_tpu")
_configured = False


def configure(level: str = "info", log_dir: Optional[str] = None,
              stdout: bool = True, tag: str = "") -> None:
    """Reference: log.Setup from flags (-log_level, -log_dir, -log_stdout)."""
    global _configured
    _logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    _logger.handlers.clear()
    fmt = logging.Formatter(
        "%(asctime)s %(levelname).1s " + (f"[{tag}] " if tag else "")
        + "%(message)s")
    if stdout:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(fmt)
        _logger.addHandler(h)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        f = logging.FileHandler(
            os.path.join(log_dir, f"paxi_tpu{('.' + tag) if tag else ''}.log"))
        f.setFormatter(fmt)
        _logger.addHandler(f)
    _configured = True


def _ensure() -> None:
    if not _configured:
        configure()


def debugf(fmt: str, *a) -> None:
    _ensure()
    _logger.debug(fmt, *a)


def infof(fmt: str, *a) -> None:
    _ensure()
    _logger.info(fmt, *a)


def warningf(fmt: str, *a) -> None:
    _ensure()
    _logger.warning(fmt, *a)


def errorf(fmt: str, *a) -> None:
    _ensure()
    _logger.error(fmt, *a)
