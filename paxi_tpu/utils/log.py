"""Leveled logger.

Reference: paxi's ``log/`` package — a glog-style leveled logger
(``Debugf/Infof/Warningf/Errorf``) writing per-process files, configured
by ``-log_dir``, ``-log_level``, ``-log_stdout`` flags [med].  Thin
wrapper over stdlib logging with the same surface.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_logger = logging.getLogger("paxi_tpu")
_configured = False


def configure(level: Optional[str] = None, log_dir: Optional[str] = None,
              stdout: bool = True, tag: str = "") -> None:
    """Reference: log.Setup from flags (-log_level, -log_dir, -log_stdout).

    ``level=None`` (or "") falls back to the ``PAXI_LOG_LEVEL`` env var,
    then "info" — so driver scripts get leveled logging from the
    environment without each re-implementing flag plumbing."""
    global _configured
    if not level:
        level = os.environ.get("PAXI_LOG_LEVEL", "info")
    _logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    _logger.handlers.clear()
    fmt = logging.Formatter(
        "%(asctime)s %(levelname).1s " + (f"[{tag}] " if tag else "")
        + "%(message)s")
    if stdout:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(fmt)
        _logger.addHandler(h)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        f = logging.FileHandler(
            os.path.join(log_dir, f"paxi_tpu{('.' + tag) if tag else ''}.log"))
        f.setFormatter(fmt)
        _logger.addHandler(f)
    _configured = True


def _ensure() -> None:
    if not _configured:
        configure()


def debugf(fmt: str, *a) -> None:
    _ensure()
    _logger.debug(fmt, *a)


def infof(fmt: str, *a) -> None:
    _ensure()
    _logger.info(fmt, *a)


def warningf(fmt: str, *a) -> None:
    _ensure()
    _logger.warning(fmt, *a)


def errorf(fmt: str, *a) -> None:
    _ensure()
    _logger.error(fmt, *a)


def metrics_dump(source, header: str = "metrics") -> None:
    """Log a metrics snapshot (a Registry or its ``snapshot()`` dict) as
    aligned info lines — one shared implementation so the driver
    scripts don't each reinvent metrics printing."""
    snap = source.snapshot() if hasattr(source, "snapshot") else source
    from paxi_tpu.metrics import pretty  # local: utils must stay light
    for line in pretty(snap).splitlines():
        infof("%s| %s", header, line)
