"""Small graph/queue helpers.

Reference: paxi lib/ — standalone data structures used by protocol
packages (a directed graph with SCC detection and BFS for EPaxos's
dependency execution, and a priority queue) [low-conf row of SURVEY
§2.1].  The EPaxos host replica carries a fused Tarjan specialised for
blocked-dependency tracking; these are the general-purpose forms.
"""

from __future__ import annotations

import heapq
from typing import Callable, Dict, Hashable, Iterable, List, Optional, Set

Node = Hashable


class Graph:
    """Directed graph over hashable nodes (paxi lib/graph.go analog)."""

    def __init__(self):
        self._adj: Dict[Node, Set[Node]] = {}

    def add_node(self, u: Node) -> None:
        self._adj.setdefault(u, set())

    def add_edge(self, u: Node, v: Node) -> None:
        self.add_node(u)
        self.add_node(v)
        self._adj[u].add(v)

    def remove(self, u: Node) -> None:
        self._adj.pop(u, None)
        for vs in self._adj.values():
            vs.discard(u)

    def nodes(self) -> List[Node]:
        return list(self._adj)

    def neighbors(self, u: Node) -> Set[Node]:
        return self._adj.get(u, set())

    def __contains__(self, u: Node) -> bool:
        return u in self._adj

    def __len__(self) -> int:
        return len(self._adj)

    # ---- traversal -----------------------------------------------------
    def bfs(self, src: Node) -> List[Node]:
        """Nodes reachable from src in BFS order (src first)."""
        seen = {src}
        order = [src]
        frontier = [src]
        while frontier:
            nxt = []
            for u in frontier:
                for v in sorted(self.neighbors(u), key=repr):
                    if v not in seen:
                        seen.add(v)
                        order.append(v)
                        nxt.append(v)
            frontier = nxt
        return order

    def scc(self) -> List[List[Node]]:
        """Strongly connected components, in reverse topological order
        (every component precedes the ones that depend on it) — the
        order EPaxos executes in.  Iterative Tarjan."""
        index: Dict[Node, int] = {}
        low: Dict[Node, int] = {}
        on_stack: Set[Node] = set()
        stack: List[Node] = []
        comps: List[List[Node]] = []
        counter = [0]

        def connect(root: Node) -> None:
            work = [(root, iter(sorted(self.neighbors(root), key=repr)))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                u, it = work[-1]
                advanced = False
                for v in it:
                    if v not in index:
                        index[v] = low[v] = counter[0]
                        counter[0] += 1
                        stack.append(v)
                        on_stack.add(v)
                        work.append((v, iter(sorted(self.neighbors(v),
                                                    key=repr))))
                        advanced = True
                        break
                    if v in on_stack:
                        low[u] = min(low[u], index[v])
                if advanced:
                    continue
                work.pop()
                if work:
                    p = work[-1][0]
                    low[p] = min(low[p], low[u])
                if low[u] == index[u]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == u:
                            break
                    comps.append(comp)

        for u in sorted(self._adj, key=repr):
            if u not in index:
                connect(u)
        return comps


class PriorityQueue:
    """Min-heap with stable insertion order on ties (paxi lib pq)."""

    def __init__(self):
        self._heap: list = []
        self._n = 0

    def push(self, priority, item) -> None:
        self._n += 1
        heapq.heappush(self._heap, (priority, self._n, item))

    def pop(self):
        if not self._heap:
            raise IndexError("pop from empty PriorityQueue")
        return heapq.heappop(self._heap)[2]

    def peek(self):
        if not self._heap:
            raise IndexError("peek on empty PriorityQueue")
        return self._heap[0][2]

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:
        return bool(self._heap)
