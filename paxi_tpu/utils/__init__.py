"""Utilities: platform selection, logging."""

from paxi_tpu.utils.platform import ensure_env_platform

__all__ = ["ensure_env_platform"]
