"""The declarative workload vocabulary: key popularity, read mixes and
arrival bursts as data.

A ``Workload`` describes the *traffic* a protocol serves — the axis
"Practical Experience Report: The Performance of Paxos in the Cloud"
(PAPERS.md) measures and the uniform closed/open-loop generators
cannot express:

- **distribution**: which keys the offered commands touch — uniform,
  Zipf(θ) (rank r drawn ∝ 1/(r+1)^θ, the canonical web-traffic skew),
  or an explicit hot set (``hot_keys`` keys soaking up ``hot_weight``
  of the draws — paxi's conflict-ratio knob, generalized).
- **read mix**: ``read_frac`` of commands are reads (no state
  mutation) — the lever leader_reads and per-key registers care about.
- **flash crowd**: timed arrival surges.  On the host the Poisson
  ramp's offered rate is multiplied by ``mult`` inside each surge
  window; in the sim the proposer's demand gate runs a ``1/mult`` duty
  cycle OUTSIDE windows so a surge offers ``mult``× demand.  ``focus``
  optionally concentrates surge draws onto the hot ranks (the
  celebrity-event shape).
- **hot-key migration**: ``migrate_every`` rotates which key IDS are
  popular every N steps (popularity RANKS are stable; the rank→key
  mapping shifts) — the adversary for ownership/steal policies.

Draws are **counter-based**: every sample is a pure integer hash of
(spec seed, group id, step/slot, lane) — no PRNG state, no shaped
whole-batch draws.  That is what lets the same spec lower onto the
lane-major sim kernels, the per-group kernels, a sharded device mesh
(each shard re-derives its slice bit-for-bit) and the host generators,
all agreeing deterministically (paxi-lint rule family PXW12x pins
this; see analysis/workload.py).

Everything is a frozen dataclass of ints/floats: hashable (a Workload
rides inside ``SimConfig``, a jit static argument), trivially
serializable (``dataclasses.asdict`` -> JSON), and reconstructible via
``from_dict``.  Like scenarios/spec.py — the environment sibling of
this module — it is dependency-free on purpose: ``sim/types.py``
carries a ``Workload`` by duck type and ``workload/compile.py`` lowers
it onto both runtimes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

# key-class label order: class id 0/1/2 = hot/warm/cold everywhere
# (kernel planes, bench rows, host histogram labels)
CLASSES = ("hot", "warm", "cold")


@dataclass(frozen=True)
class FlashCrowd:
    """Arrival surges: windows ``[start + k*period, .. + duration)``
    (``period=0``: a single window).  Times are sim steps on the sim
    runtime and rate-ramp step indices on the host."""

    start: int = 20
    period: int = 0       # steps between window starts (0: one-shot)
    duration: int = 10    # steps each surge lasts
    mult: float = 4.0     # arrival-rate multiplier during a surge
    focus: float = 0.0    # extra P(draw lands on the hot ranks) inside
    # a surge window (0 = the surge keeps the base distribution)


@dataclass(frozen=True)
class Workload:
    """A key-popularity / read-mix / burst workload (module docstring).

    ``hot_cut``/``warm_cut`` split popularity RANKS into the hot/warm/
    cold classes whose latency is reported separately: ranks below
    ``ceil(hot_cut*K)`` are hot, below ``ceil(warm_cut*K)`` warm, the
    rest cold (``dist="hotset"`` pins the hot class to its explicit
    ``hot_keys`` instead)."""

    name: str = "workload"
    dist: str = "uniform"      # uniform | zipf | hotset
    theta: float = 0.99        # zipf: P(rank r) ∝ 1/(r+1)^theta
    hot_keys: int = 4          # hotset: size of the hot set
    hot_weight: float = 0.9    # hotset: P(draw lands in the hot set)
    read_frac: float = 0.0     # fraction of commands that are reads
    flash: Optional[FlashCrowd] = None
    migrate_every: int = 0     # rotate the hot key ids every N steps
    hot_cut: float = 0.05      # class split: top ranks -> "hot"
    warm_cut: float = 0.30     # next ranks -> "warm"; rest "cold"
    seed: int = 0              # spec-level salt folded into every draw

    # ---- validation -----------------------------------------------------
    def validate(self, n_keys: int) -> "Workload":
        """Raise ValueError on an inconsistent spec; returns self so
        call sites can chain."""
        if n_keys < 1:
            raise ValueError(f"workload {self.name!r}: n_keys must be "
                             f">= 1, got {n_keys}")
        if self.dist not in ("uniform", "zipf", "hotset"):
            raise ValueError(f"workload {self.name!r}: unknown dist "
                             f"{self.dist!r}")
        if self.dist == "zipf" and self.theta <= 0:
            raise ValueError(f"workload {self.name!r}: zipf theta must "
                             f"be > 0, got {self.theta}")
        if self.dist == "hotset":
            if not 1 <= self.hot_keys <= n_keys:
                raise ValueError(
                    f"workload {self.name!r}: hot_keys={self.hot_keys} "
                    f"outside 1..{n_keys}")
            if not 0.0 < self.hot_weight <= 1.0:
                raise ValueError(f"workload {self.name!r}: hot_weight "
                                 "must be in (0, 1]")
        if not 0.0 <= self.read_frac <= 1.0:
            raise ValueError(f"workload {self.name!r}: read_frac must "
                             "be in [0, 1]")
        if not 0.0 < self.hot_cut <= self.warm_cut <= 1.0:
            raise ValueError(f"workload {self.name!r}: need 0 < hot_cut"
                             f"={self.hot_cut} <= warm_cut="
                             f"{self.warm_cut} <= 1")
        if self.migrate_every < 0:
            raise ValueError(f"workload {self.name!r}: migrate_every "
                             "must be >= 0")
        if self.flash is not None:
            fl = self.flash
            if fl.start < 0 or fl.duration < 1 or fl.period < 0:
                raise ValueError(f"workload {self.name!r}: flash needs "
                                 "start >= 0, duration >= 1 and "
                                 "period >= 0")
            if fl.period and fl.duration > fl.period:
                raise ValueError(f"workload {self.name!r}: flash "
                                 f"duration={fl.duration} must be <= "
                                 f"period={fl.period}")
            if fl.mult < 1.0:
                raise ValueError(f"workload {self.name!r}: flash mult "
                                 "must be >= 1")
            if not 0.0 <= fl.focus <= 1.0:
                raise ValueError(f"workload {self.name!r}: flash focus "
                                 "must be in [0, 1]")
        return self

    # ---- (de)serialization ----------------------------------------------
    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Workload":
        """Rebuild from ``dataclasses.asdict`` output after a JSON
        round-trip — the trace-meta / artifact path."""
        fl = d.get("flash")
        flash = FlashCrowd(start=int(fl["start"]),
                           period=int(fl.get("period", 0)),
                           duration=int(fl.get("duration", 1)),
                           mult=float(fl.get("mult", 1.0)),
                           focus=float(fl.get("focus", 0.0))) \
            if fl else None
        return Workload(name=str(d.get("name", "workload")),
                        dist=str(d.get("dist", "uniform")),
                        theta=float(d.get("theta", 0.99)),
                        hot_keys=int(d.get("hot_keys", 4)),
                        hot_weight=float(d.get("hot_weight", 0.9)),
                        read_frac=float(d.get("read_frac", 0.0)),
                        flash=flash,
                        migrate_every=int(d.get("migrate_every", 0)),
                        hot_cut=float(d.get("hot_cut", 0.05)),
                        warm_cut=float(d.get("warm_cut", 0.30)),
                        seed=int(d.get("seed", 0)))
