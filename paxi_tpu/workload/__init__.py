"""Production workload engine: key-popularity skew, read mixes and
flash crowds as data.

Declarative ``Workload`` specs (spec.py) compiled onto the two
runtimes' command paths from stateless counter-based draws
(compile.py): the sim kernels derive per-step key/read/class planes
from (spec seed, global group id, absolute slot) hashes — identical
across lane-major, per-group and sharded lowerings, bit-for-bit under
pinned replay — and the host generators (``OpenLoopBenchmark``/
``Benchmark``) derive the same spec's per-op keys, write flags and
flash-crowd rate multipliers from the same hash family.  Key classes
(hot/warm/cold) label per-class latency histograms on both sides.
The environment sibling of ``paxi_tpu/scenarios``; see README
"Workloads".
"""

from paxi_tpu.workload.spec import CLASSES, FlashCrowd, Workload
from paxi_tpu.workload.compile import (FLASH, HOTRANGE, MIGRATE, NAMED,
                                       UNIFORM, ZIPF99, apply_workload,
                                       class_cuts, class_plane, class_split,
                                       demand_gate, describe, flash_on,
                                       host_rates, host_sampler, icdf_table,
                                       key_plane, named_workload, rank_plane,
                                       rank_pmf, read_plane, surge_steps)

__all__ = ["Workload", "FlashCrowd", "CLASSES", "NAMED", "UNIFORM",
           "ZIPF99", "FLASH", "HOTRANGE", "MIGRATE",
           "named_workload", "describe", "apply_workload", "class_cuts",
           "icdf_table", "rank_pmf", "key_plane", "rank_plane",
           "read_plane", "class_plane", "flash_on", "demand_gate",
           "class_split", "host_sampler", "host_rates", "surge_steps"]
