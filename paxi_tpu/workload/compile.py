"""Compile Workloads onto the two runtimes + the named-workload registry.

Sim side: a workload rides INSIDE the SimConfig (``apply_workload``) —
static, so the jit cache, ``continue_run``'s carry cache and a trace's
``sim_cfg`` meta all pin it like the geometry.  Kernels derive each
command's key id, read flag and key class from **counter-based
draws**: a pure integer hash of (spec seed, GLOBAL group id, absolute
slot/step, channel).  Nothing is drawn ahead of time and nothing is
shaped over the whole batch, so

- lane-major and per-group lowerings of the same spec produce
  bit-identical command planes (the hash doesn't know the layout),
- a sharded mesh re-derives its group slice exactly (each shard
  offsets its local group ids to global ones — parallel/mesh.py),
- pinned replay is bit-for-bit: the plane is a function, not state.

The popularity distribution itself is lowered once per (spec, K) into
a quantized inverse-CDF rank table (``icdf_table``, pure python,
lru-cached) that embeds as a jnp constant: a draw is hash -> quantile
-> table[quantile] -> popularity rank, then hot-key migration rotates
rank->key id by epoch.  Key CLASSES (hot/warm/cold) are rank ranges,
so the class label follows the popular keys through a migration.

Host side: ``host_sampler`` derives the i-th op of a generator stream
from the same hash family (python ints, no ``random``), and
``host_rates``/``surge_steps`` lower a FlashCrowd onto the open-loop
Poisson ramp as per-step rate multipliers.  ``OpenLoopBenchmark``/
``Benchmark`` consume these via their ``workload=`` hook and label
per-op latency histograms with ``key_class`` so /metrics snapshots and
bench rows report per-class p50/p99.

paxi-lint family PXW12x (analysis/workload.py) pins the purity
contract for this package: no ``random``/``np.random``/``jax.random``
anywhere — counter-based draws only.
"""

from __future__ import annotations

import dataclasses
import math
from functools import lru_cache
from typing import Dict, Optional, Sequence, Tuple

from paxi_tpu.workload.spec import CLASSES, FlashCrowd, Workload

# quantized inverse-CDF resolution: draws use the hash's top _QBITS
# bits, so every rank with probability >= 1/Q is representable and
# frequency error per rank is <= 1/Q
_QBITS = 12
Q = 1 << _QBITS
_QSHIFT = 32 - _QBITS

# draw channels: each derived quantity hashes a distinct channel so
# key/read/gate draws at the same (group, slot) are independent.
# Channel values are spaced so per-replica offsets (wpaxos demand adds
# the replica index) cannot collide across channels.
CH_KEY = 0x000      # key-popularity rank
CH_READ = 0x100     # read-vs-write coin
CH_GATE = 0x200     # flash-crowd demand duty cycle
CH_DEMAND = 0x300   # wpaxos per-replica object demand (+ replica idx)
CH_FOCUS = 0x400    # host: surge hot-focus coin
CH_HOT = 0x500      # host: surge hot-rank choice

# mix multipliers (odd 32-bit constants; the avalanche is _h32's job)
_C_GID = 0x9E3779B1
_C_SLOT = 0x85EBCA77
_C_CHAN = 0xC2B2AE3D
_C_SEED = 0x27D4EB2F


# ---- the popularity table (pure python, shared by both runtimes) ---------

@lru_cache(maxsize=None)
def icdf_table(wl: Workload, n_keys: int) -> Tuple[int, ...]:
    """Quantized inverse CDF: ``table[q]`` is the popularity rank drawn
    at quantile ``(q + 0.5) / Q``.  Rank 0 is the most popular."""
    K = max(int(n_keys), 1)
    if wl.dist == "zipf":
        w = [1.0 / math.pow(r + 1, wl.theta) for r in range(K)]
    elif wl.dist == "hotset":
        h = min(wl.hot_keys, K)
        if h >= K:
            w = [1.0] * K
        else:
            hw = wl.hot_weight
            w = [hw / h] * h + [(1.0 - hw) / (K - h)] * (K - h)
    else:
        w = [1.0] * K
    total = sum(w)
    acc, cdf = 0.0, []
    for x in w:
        acc += x / total
        cdf.append(acc)
    table = []
    r = 0
    for q in range(Q):
        target = (q + 0.5) / Q
        while r < K - 1 and cdf[r] < target:
            r += 1
        table.append(r)
    return tuple(table)


def rank_pmf(wl: Workload, n_keys: int) -> Tuple[float, ...]:
    """The per-rank probability the quantized table actually realizes
    (uniform draws over table entries) — the reference distribution
    for frequency tests."""
    counts = [0] * max(int(n_keys), 1)
    for r in icdf_table(wl, n_keys):
        counts[r] += 1
    return tuple(c / Q for c in counts)


def class_cuts(wl: Workload, n_keys: int) -> Tuple[int, int]:
    """Rank thresholds of the hot/warm/cold split: ranks below
    ``n_hot`` are hot, below ``n_warm`` warm, the rest cold."""
    K = max(int(n_keys), 1)
    if wl.dist == "hotset":
        n_hot = min(wl.hot_keys, K)
    else:
        n_hot = min(max(1, math.ceil(wl.hot_cut * K)), K)
    n_warm = min(max(n_hot, math.ceil(wl.warm_cut * K)), K)
    return n_hot, n_warm


def class_of_rank(wl: Workload, n_keys: int, rank: int) -> int:
    n_hot, n_warm = class_cuts(wl, n_keys)
    return 0 if rank < n_hot else (1 if rank < n_warm else 2)


@lru_cache(maxsize=None)
def obj_class_table(wl: Workload, n_keys: int,
                    n_objects: int) -> Tuple[int, ...]:
    """Key class per wpaxos OBJECT: demand maps key -> object by
    ``key % n_objects``, so object ``o``'s most popular resident at
    epoch 0 is rank ``o`` and its class labels the object.  Static —
    a migration rotates key ids, not ranks, so a migrating spec's
    per-object labels drift by design (documented in the README)."""
    return tuple(class_of_rank(wl, n_keys, min(o, n_keys - 1))
                 for o in range(n_objects))


def _frac_thr(frac: float) -> int:
    """uint32 threshold with P(u < thr) = frac (clamped)."""
    return max(0, min(int(frac * 4294967296.0), 0xFFFFFFFF))


# ---- sim lowering (jnp; deferred import like metrics/lathist.py) ---------

def _h32(x):
    """lowbias32-style avalanche on uint32 planes."""
    import jax.numpy as jnp
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _draw_u(wl: Workload, gid, slot, chan):
    """One uint32 per (spec seed, group id, slot/step, channel) —
    the counter-based draw every derived plane starts from.  ``gid``/
    ``slot``/``chan`` broadcast like jnp operands."""
    import jax.numpy as jnp
    x = (jnp.asarray(gid).astype(jnp.uint32) * jnp.uint32(_C_GID)
         ^ jnp.asarray(slot).astype(jnp.uint32) * jnp.uint32(_C_SLOT)
         ^ jnp.asarray(chan).astype(jnp.uint32) * jnp.uint32(_C_CHAN)
         ^ jnp.uint32(wl.seed & 0xFFFFFFFF) * jnp.uint32(_C_SEED))
    return _h32(x)


def rank_plane(wl: Workload, n_keys: int, gid, slot, chan=CH_KEY):
    """Popularity ranks (int32) drawn at (group, absolute slot)."""
    import jax.numpy as jnp
    u = _draw_u(wl, gid, slot, chan)
    q = (u >> jnp.uint32(_QSHIFT)).astype(jnp.int32)
    table = jnp.asarray(icdf_table(wl, n_keys), jnp.int32)
    return table[q]


def key_plane(wl: Workload, n_keys: int, gid, slot, chan=CH_KEY):
    """Key ids (int32) at (group, absolute slot): rank draw + hot-key
    migration (the rank->key rotation advances one hot-set width per
    ``migrate_every`` steps, every replica deriving it identically
    from the absolute slot — nothing rides the wire)."""
    import jax.numpy as jnp
    rank = rank_plane(wl, n_keys, gid, slot, chan)
    if wl.migrate_every <= 0:
        return rank
    epoch = jnp.floor_divide(jnp.asarray(slot).astype(jnp.int32),
                             wl.migrate_every)
    n_hot, _ = class_cuts(wl, n_keys)
    return jnp.remainder(rank + epoch * n_hot, n_keys)


def read_plane(wl: Workload, gid, slot):
    """Read flags (bool) at (group, absolute slot)."""
    import jax.numpy as jnp
    if wl.read_frac <= 0.0:
        return jnp.zeros(jnp.broadcast_shapes(jnp.shape(gid),
                                              jnp.shape(slot)), bool)
    if wl.read_frac >= 1.0:
        return jnp.ones(jnp.broadcast_shapes(jnp.shape(gid),
                                             jnp.shape(slot)), bool)
    u = _draw_u(wl, gid, slot, CH_READ)
    return u < jnp.uint32(_frac_thr(wl.read_frac))


def class_plane(wl: Workload, n_keys: int, gid, slot, chan=CH_KEY):
    """Key-class ids (int32; 0/1/2 = hot/warm/cold, spec.CLASSES
    order) of the commands at (group, absolute slot) — the rank-range
    label, so it tracks the popular keys through migrations."""
    import jax.numpy as jnp
    rank = rank_plane(wl, n_keys, gid, slot, chan)
    n_hot, n_warm = class_cuts(wl, n_keys)
    return jnp.where(rank < n_hot, 0,
                     jnp.where(rank < n_warm, 1, 2)).astype(jnp.int32)


def flash_on(wl: Workload, t):
    """Traced bool: is sim step ``t`` inside a surge window?  None for
    flashless specs (static python decision — the kernel specializes)."""
    import jax.numpy as jnp
    fl = wl.flash
    if fl is None:
        return None
    t = jnp.asarray(t).astype(jnp.int32)
    if fl.period > 0:
        ph = jnp.remainder(t - fl.start, fl.period)
        return (t >= fl.start) & (ph < fl.duration)
    return (t >= fl.start) & (t < fl.start + fl.duration)


def demand_gate(wl: Workload, gid, t, chan=CH_GATE):
    """Flash-crowd lowering for the sim's closed proposer loop: the
    sim cannot over-offer like the host's open loop, so OUTSIDE surge
    windows new proposals run a ``1/mult`` duty cycle (counter-based
    coin per (group, step)) and surges lift the gate — a window offers
    ``mult``x the baseline demand.  None when the spec has no flash
    component (the kernel keeps its always-on propose path)."""
    import jax.numpy as jnp
    fl = wl.flash
    if fl is None:
        return None
    u = _draw_u(wl, gid, t, chan)
    duty = u < jnp.uint32(_frac_thr(1.0 / fl.mult))
    return flash_on(wl, t) | duty


# ---- SimConfig plumbing --------------------------------------------------

def apply_workload(cfg, wl: Optional[Workload]):
    """The SimConfig that serves ``wl``'s traffic (validated against
    the config's key space).  No-op for ``wl=None``."""
    if wl is None:
        return cfg
    return cfg.with_(workload=wl.validate(cfg.n_keys))


def class_split(state) -> Dict[str, Dict]:
    """Fold the kernels' per-class ``m_wl_hist_*``/``m_wl_sum_*``
    measurement planes (group-major final state) into per-class
    latency summaries — the bench-row / CLI form of the per-key-class
    split.  Empty dict when the run was workloadless."""
    import numpy as np

    from paxi_tpu.metrics import lathist

    out: Dict[str, Dict] = {}
    if not isinstance(state, dict):
        return out
    for nm in CLASSES:
        h = state.get(f"m_wl_hist_{nm}")
        if h is None:
            continue
        counts = lathist.plane_total(h)
        sums = int(np.asarray(state.get(f"m_wl_sum_{nm}", 0),
                              dtype=np.int64).sum())
        out[nm] = lathist.summarize(counts, sums)
    return out


# ---- host lowering (python ints; same hash family, no random) ------------

def _h32i(x: int) -> int:
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def _draw_ui(wl: Workload, stream: int, i: int, chan: int) -> int:
    return _h32i((stream * _C_GID) ^ (i * _C_SLOT) ^ (chan * _C_CHAN)
                 ^ ((wl.seed & 0xFFFFFFFF) * _C_SEED))


def host_sampler(wl: Workload, n_keys: int, stream: int = 0):
    """The host generators' per-op derivation: ``sample(i, surge=...,
    epoch=...)`` -> ``(key, write, key_class)`` for the i-th op of
    generator stream ``stream`` — deterministic in (spec, stream, i),
    mirroring the sim's (group, slot) counter draws.  ``surge`` applies
    the FlashCrowd ``focus`` re-aim; ``epoch`` is the migration epoch
    (the host driver derives it from its own clock/ramp position)."""
    K = max(int(n_keys), 1)
    table = icdf_table(wl, K)
    n_hot, n_warm = class_cuts(wl, K)
    fl = wl.flash
    focus_thr = _frac_thr(fl.focus) if fl is not None else 0
    read_thr = _frac_thr(wl.read_frac)
    always_read = wl.read_frac >= 1.0
    never_read = wl.read_frac <= 0.0

    def sample(i: int, surge: bool = False,
               epoch: int = 0) -> Tuple[int, bool, str]:
        rank = table[_draw_ui(wl, stream, i, CH_KEY) >> _QSHIFT]
        if surge and focus_thr \
                and _draw_ui(wl, stream, i, CH_FOCUS) < focus_thr:
            rank = _draw_ui(wl, stream, i, CH_HOT) % n_hot
        key = rank
        if wl.migrate_every > 0 and epoch:
            key = (rank + epoch * n_hot) % K
        if always_read:
            write = False
        elif never_read:
            write = True
        else:
            write = _draw_ui(wl, stream, i, CH_READ) >= read_thr
        cls = CLASSES[0 if rank < n_hot else (1 if rank < n_warm else 2)]
        return key, write, cls

    return sample


def surge_steps(wl: Workload, n_steps: int) -> Tuple[bool, ...]:
    """FlashCrowd window membership per host ramp step (python twin of
    ``flash_on`` over step indices 0..n_steps-1)."""
    fl = wl.flash
    if fl is None:
        return tuple(False for _ in range(n_steps))
    out = []
    for t in range(n_steps):
        if t < fl.start:
            out.append(False)
        elif fl.period > 0:
            out.append((t - fl.start) % fl.period < fl.duration)
        else:
            out.append(t < fl.start + fl.duration)
    return tuple(out)


def host_rates(wl: Workload, rates: Sequence[float]) -> Tuple[float, ...]:
    """The effective offered-rate ramp: surge steps multiply the
    target rate by ``mult`` (the host half of the flash lowering —
    the Poisson arrival process itself is the generator's)."""
    fl = wl.flash
    if fl is None:
        return tuple(float(r) for r in rates)
    on = surge_steps(wl, len(rates))
    return tuple(float(r) * (fl.mult if s else 1.0)
                 for r, s in zip(rates, on))


# ---- named workloads -----------------------------------------------------
# The built-in catalog (CLI `workload list|run -workload NAME`,
# bench-host's -workload flag, bench_all's workload axis).  All entries
# share the read mix so distribution is the only axis that moves
# between a row and its uniform control.
UNIFORM = Workload(name="uniform", dist="uniform", read_frac=0.5)

ZIPF99 = Workload(name="zipf99", dist="zipf", theta=0.99,
                  read_frac=0.5)

# zipf skew + periodic surges that re-aim half the draws at the hot
# ranks (the celebrity-event shape)
FLASH = Workload(name="flash", dist="zipf", theta=0.99, read_frac=0.5,
                 flash=FlashCrowd(start=30, period=60, duration=12,
                                  mult=4.0, focus=0.5))

# explicit hot set: the shard router's hot-range adversary and the
# ownership-steal stress shape
HOTRANGE = Workload(name="hotrange", dist="hotset", hot_keys=8,
                    hot_weight=0.9, read_frac=0.2)

# zipf whose popular key ids rotate mid-run — the migration adversary
# for ownership/steal policies
MIGRATE = Workload(name="migrate", dist="zipf", theta=0.99,
                   read_frac=0.5, migrate_every=40)

NAMED: Dict[str, Workload] = {w.name: w for w in (
    UNIFORM, ZIPF99, FLASH, HOTRANGE, MIGRATE)}


def named_workload(name: str) -> Workload:
    if name not in NAMED:
        raise KeyError(f"unknown workload {name!r}; "
                       f"have {sorted(NAMED)}")
    return NAMED[name]


def describe(wl: Workload, n_keys: int = 64) -> Dict:
    """One-line-able summary for `workload list`."""
    n_hot, n_warm = class_cuts(wl, n_keys)
    out: Dict = {"name": wl.name, "dist": wl.dist,
                 "read_frac": wl.read_frac,
                 "classes": {"hot_ranks": n_hot,
                             "warm_ranks": n_warm - n_hot,
                             "at_keys": n_keys}}
    if wl.dist == "zipf":
        out["theta"] = wl.theta
    if wl.dist == "hotset":
        out["hot_keys"] = wl.hot_keys
        out["hot_weight"] = wl.hot_weight
    if wl.flash is not None:
        out["flash"] = dataclasses.asdict(wl.flash)
    if wl.migrate_every:
        out["migrate_every"] = wl.migrate_every
    return out
