"""Runnable tour of the workload engine: one spec, two runtimes.

    python -m paxi_tpu.workload.demo

Walks the named catalog, shows the counter-draw determinism that makes
a spec portable across lowerings (lane-major vs per-group paxos on the
same zipf99 spec -> bit-identical kv planes), the per-key-class
latency split, the wpaxos steal contrast under skew, and the host
sampler agreeing with the sim's planes draw for draw.  Everything
here is asserted, so the demo doubles as a smoke script; it prints
one JSON line per stage.
"""

from __future__ import annotations

import json


def main() -> int:
    import numpy as np

    from paxi_tpu.protocols import sim_protocol
    from paxi_tpu.sim import SimConfig, simulate
    from paxi_tpu.workload import (NAMED, ZIPF99, apply_workload,
                                   class_split, describe, host_sampler,
                                   key_plane, named_workload,
                                   read_plane)

    # 1. the catalog
    print(json.dumps({"stage": "catalog",
                      "specs": [describe(NAMED[n])["name"]
                                for n in sorted(NAMED)]}))

    # 2. one spec, both sim lowerings: bit-identical command effects
    cfg = apply_workload(SimConfig(n_replicas=3, n_slots=16,
                                   n_keys=64), ZIPF99)
    res = {n: simulate(sim_protocol(n), cfg, 8, 80, seed=3)
           for n in ("paxos", "paxos_pg")}
    kv = {n: np.asarray(r.state["kv"]) for n, r in res.items()}
    assert (kv["paxos"] == kv["paxos_pg"]).all()
    assert all(int(r.violations) == 0 for r in res.values())
    split = class_split(res["paxos"].state)
    print(json.dumps({
        "stage": "sim-lowering-parity", "workload": "zipf99",
        "kv_bit_identical": True,
        "committed": int(res["paxos"].metrics["committed_slots"]),
        "key_class_latency": split}))

    # 3. host sampler == sim planes (same hash family, python ints)
    slots = np.arange(64)
    sim_keys = np.asarray(key_plane(ZIPF99, 64, 2, slots))
    sim_reads = np.asarray(read_plane(ZIPF99, 2, slots))
    sample = host_sampler(ZIPF99, 64, stream=2)
    agree = all(sample(i)[0] == sim_keys[i]
                and sample(i)[1] == (not sim_reads[i])
                for i in range(64))
    assert agree
    print(json.dumps({"stage": "host-sim-agreement", "stream": 2,
                      "draws": 64, "agree": agree}))

    # 4. skew churns wpaxos ownership; the uniform control does not
    base = SimConfig(n_replicas=9, n_zones=3, n_slots=16, n_keys=32,
                     n_objects=16, steal_threshold=4, locality=0.8)
    steals = {}
    for name in ("uniform", "zipf99"):
        wcfg = apply_workload(base, named_workload(name))
        r = simulate(sim_protocol("wpaxos"), wcfg, 8, 120, seed=0)
        assert int(r.violations) == 0
        steals[name] = int(r.metrics["steals"])
    print(json.dumps({"stage": "wpaxos-steal-contrast",
                      "steals": steals,
                      "skew_drives_stealing":
                          steals["zipf99"] > steals["uniform"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
