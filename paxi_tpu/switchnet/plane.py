"""Sim mirror of the in-fabric consensus tier: switch-acceptor
registers + NOPaxos-style sequencer as lane-major carry planes.

The host runtime interposes a ``SwitchTier`` (switchnet/switch.py) on
the virtual-clock fabric's wire; the sim runtime cannot interpose on
its lock-step exchange, so the switch lives IN THE SCAN CARRY instead:
a frame "passes through the switch" at the step its outbox is built
(the switch sits mid-fabric, before the delay wheel), and the vote it
casts becomes visible to the leader at the NEXT step — exactly one
fabric delivery, where the classic P2a->P2b round trip costs two.
That one-step visibility is free: a kernel step reads the PREVIOUS
step's state planes by construction.

Register-state contract (mirrored bit-for-bit by the host tier):

- **bounded**: a fixed ``cfg.sw_window`` register file per group —
  ``sw_vbal``/``sw_vcmd``/``sw_reg_seq`` over absolute slots
  ``[sw_base, sw_base + W)`` plus the scalar promise ``sw_bal`` and
  sequencer counter ``sw_seq``.  No heap, no per-slot maps.
- **overflow -> replicas**: a frame whose slot falls outside the file
  gets no vote and no stamp; the leader falls back to the classic
  majority-P2b path (which always runs underneath).
- **eviction is execution-gated**: ``sw_base`` advances only past
  ``min_r execute`` — a register recycles only once EVERY replica has
  executed (hence durably committed) past its slot, so a fast-path
  commit can never be evicted into thin air.
- **recovery reads the registers**: a phase-1 winner folds the
  register file into its log before the P1b merge
  (``recovery_fold``), so the in-network write quorum {switch}
  intersects every recovery quorum by construction — the obligation
  paxi-lint's PXQ505 pins statically.
- **sequencer churn** (scenario ``SwitchChurn``, compiled into the
  static ``cfg.sw_down_*`` knobs): during a down window the switch
  neither votes nor stamps (register state and the ballot promise
  persist — failover migrates the bounded file); each window end
  bumps the session epoch.  ``down_t``/``session_t`` evaluate the
  SAME arithmetic as ``scenarios.schedule.switch_down_at`` /
  ``switch_session_at`` on a traced step index.
"""

from __future__ import annotations

import jax.numpy as jnp

from paxi_tpu.sim.ring import shift_row, shift_window
from paxi_tpu.sim.types import SimConfig

NO_CMD = -1   # empty value register (ballot_ring.NO_CMD)
NO_SEQ = -1   # unstamped frame / empty sequence register

# the switch-plane keys a switchnet kernel carries
KEYS = ("sw_bal", "sw_base", "sw_vbal", "sw_vcmd", "sw_reg_seq",
        "sw_seq")


def init_planes(cfg: SimConfig, n_groups: int):
    """Zeroed switch planes (lane-major, group axis last)."""
    if cfg.sw_window > cfg.n_slots:
        raise ValueError(
            f"sw_window={cfg.sw_window} > n_slots={cfg.n_slots}: the "
            "register file must fit the ring for recovery alignment")
    W, G = cfg.sw_window, n_groups
    i32 = jnp.int32
    return dict(
        sw_bal=jnp.zeros((G,), i32),          # switch ballot promise
        sw_base=jnp.zeros((G,), i32),         # abs slot of register 0
        sw_vbal=jnp.zeros((W, G), i32),       # vote registers: ballot
        sw_vcmd=jnp.full((W, G), NO_CMD, i32),  # vote registers: value
        sw_reg_seq=jnp.full((W, G), NO_SEQ, i32),  # stamped seq per slot
        sw_seq=jnp.zeros((G,), i32),          # next sequence number
    )


# ---- sequencer-churn schedule (static cfg knobs x traced step) ----------
def down_t(cfg: SimConfig, t):
    """Traced twin of ``scenarios.schedule.switch_down_at`` on the
    static ``cfg.sw_down_*`` knobs."""
    start, period, for_ = (cfg.sw_down_start, cfg.sw_down_period,
                           cfg.sw_down_for)
    if start < 0 or for_ < 1:
        return jnp.zeros((), bool)
    phase = (t - start) % period if period else (t - start)
    return (t >= start) & (phase < for_)


def session_t(cfg: SimConfig, t):
    """Traced twin of ``scenarios.schedule.switch_session_at``."""
    start, period, for_ = (cfg.sw_down_start, cfg.sw_down_period,
                           cfg.sw_down_for)
    if start < 0 or for_ < 1:
        return jnp.zeros((), jnp.int32)
    ended = t >= start + for_
    if not period:
        return ended.astype(jnp.int32)
    return jnp.where(ended,
                     1 + (t - start - for_) // period,
                     0).astype(jnp.int32)


# ---- register-file <-> ring alignment -----------------------------------
def align_to_ring(reg, sw_base, base, n_slots: int, fill):
    """View a ``(W, G)`` register plane through each replica's ring:
    ``out[r, i, g] = reg[i + base[r, g] - sw_base[g], g]`` (``fill``
    outside the file).  Pure pad+shift — no gathers beyond the shared
    ring primitive."""
    W, G = reg.shape
    pad = jnp.full((n_slots - W, G), fill, reg.dtype)
    row = jnp.concatenate([reg, pad], axis=0)        # (S, G)
    return shift_row(row, base - sw_base[None, :], fill)


# ---- the switch observing the wire --------------------------------------
def observe_p1a(sw, out_p1a):
    """Phase-1 passes through the fabric: the switch PROMISES to the
    highest ballot it carries (so a deposed leader's later frames get
    no vote) — the prepare-through-the-switch fence.  Promises stay
    active during down windows (control-plane path), mirroring the
    host tier."""
    hi = jnp.max(jnp.where(out_p1a["valid"], out_p1a["bal"], 0),
                 axis=(0, 1))                          # (G,)
    return dict(sw, sw_bal=jnp.maximum(sw["sw_bal"], hi))


def observe_p2a(sw, out_p2a, cfg: SimConfig, t):
    """The switch votes on P2a frames in flight and stamps them with
    the ordered-multicast (session, sequence) pair.

    Frames are broadcast-uniform over the dst axis (propose_write), so
    the per-src scalars come from dst column 0.  Among simultaneous
    proposers the switch serves the highest ballot >= its promise —
    the others pass through unvoted/unstamped (they are stale).  A
    re-sent frame (same ballot, slot already registered) keeps its
    ORIGINAL stamp: the register remembers, which is what makes a
    gap-agreement retransmit carry the sequence number the replicas
    are waiting on.

    Returns ``(sw', stamp)`` where ``stamp`` carries per-src
    ``sess``/``seq`` planes ((R, G), ``NO_SEQ`` where unstamped) plus
    the per-group ``voted`` and ``overflow`` masks."""
    R = out_p2a["valid"].shape[0]
    W = sw["sw_vbal"].shape[0]
    ridx = jnp.arange(R, dtype=jnp.int32)
    widx = jnp.arange(W, dtype=jnp.int32)

    valid = out_p2a["valid"][:, 0, :]                  # (R, G)
    bal = out_p2a["bal"][:, 0, :]
    slot = out_p2a["slot"][:, 0, :]
    cmd = out_p2a["cmd"][:, 0, :]

    b_in = jnp.where(valid, bal, -1)
    src = jnp.argmax(b_in, axis=0).astype(jnp.int32)   # (G,)
    p_bal = jnp.max(b_in, axis=0)
    p_has = p_bal > 0
    p_slot = jnp.zeros_like(p_bal)
    p_cmd = jnp.full_like(p_bal, NO_CMD)
    for r in range(R):
        p_slot = jnp.where(src == r, slot[r], p_slot)
        p_cmd = jnp.where(src == r, cmd[r], p_cmd)

    down = down_t(cfg, t)
    active = p_has & ~down & (p_bal >= sw["sw_bal"])
    rel = p_slot - sw["sw_base"]
    inw = (rel >= 0) & (rel < W)
    overflow = active & ~inw

    oh = (widx[:, None] == rel[None, :]) & (active & inw)[None, :]
    upd = oh & (p_bal[None, :] >= sw["sw_vbal"])
    fresh = upd & ((p_bal[None, :] > sw["sw_vbal"])
                   | (sw["sw_reg_seq"] < 0))
    sw_vbal = jnp.where(upd, p_bal[None, :], sw["sw_vbal"])
    sw_vcmd = jnp.where(upd, p_cmd[None, :], sw["sw_vcmd"])
    stamp_now = jnp.any(fresh, axis=0)                 # (G,)
    sw_reg_seq = jnp.where(fresh, sw["sw_seq"][None, :],
                           sw["sw_reg_seq"])
    voted = jnp.any(upd, axis=0)                       # (G,)
    frame_seq = jnp.sum(jnp.where(oh & upd, sw_reg_seq, 0), axis=0)
    frame_seq = jnp.where(voted, frame_seq, NO_SEQ)

    sess = session_t(cfg, t)
    is_src = ridx[:, None] == src[None, :]             # (R, G)
    stamp = {
        "seq": jnp.where(is_src & voted[None, :], frame_seq[None, :],
                         NO_SEQ),
        "sess": jnp.where(is_src & voted[None, :], sess, NO_SEQ),
        "voted": voted,
        "overflow": overflow,
    }
    sw = dict(sw, sw_bal=jnp.where(active,
                                   jnp.maximum(sw["sw_bal"], p_bal),
                                   sw["sw_bal"]),
              sw_vbal=sw_vbal, sw_vcmd=sw_vcmd, sw_reg_seq=sw_reg_seq,
              sw_seq=sw["sw_seq"] + stamp_now)
    return sw, stamp


# ---- leader-side fast path + recovery -----------------------------------
def fast_commit_mask(sw, st, is_leader, n_slots: int):
    """In-network acceptance: slots whose register holds a vote at MY
    ballot commit now — the vote was cast when the frame passed the
    switch last step, so the leader commits after ONE fabric delivery
    instead of the P2a->P2b round trip.  The value equality guard is
    belt-and-braces (same ballot implies same proposer and binding)."""
    al_vbal = align_to_ring(sw["sw_vbal"], sw["sw_base"], st["base"],
                            n_slots, 0)
    al_vcmd = align_to_ring(sw["sw_vcmd"], sw["sw_base"], st["base"],
                            n_slots, NO_CMD)
    return (is_leader[:, None, :] & st["proposed"] & ~st["log_commit"]
            & (al_vbal > 0) & (al_vbal == st["ballot"][:, None, :])
            & (al_vcmd == st["log_cmd"]) & (st["log_cmd"] != NO_CMD))


def apply_fast_commits(sw, st, is_leader, n_slots: int):
    """Apply the in-network acceptances to the leader's log (the
    write half of ``fast_commit_mask`` — ring-plane writes live here
    with the rest of the audited switch machinery).  Returns
    ``(st', newly_fast)``."""
    newly = fast_commit_mask(sw, st, is_leader, n_slots)
    return {**st, "log_commit": st["log_commit"] | newly}, newly


def gap_reopen(st, oh_gr):
    """Gap agreement, leader half for in-flight frames: re-open the
    requested slot for immediate re-proposal (it keeps its original
    stamp — the register remembers) instead of waiting out
    ``retry_timeout``."""
    return {**st,
            "proposed": st["proposed"] & ~(oh_gr & ~st["log_commit"])}


def noop_commit_holes(st, gap, frame_slot, sidx):
    """THE SEEDED BUG of the ``switchpaxos_nogap`` twin (host twin:
    protocols/switchpaxos/nogap.py) — never called by the real
    protocol: on a detected stamp gap, unilaterally NOOP-commit the
    empty slots below the arriving frame ("the multicast is ordered,
    so a gap must be a NOOP").  The leader commits real commands
    there, so committed values diverge across replicas — the
    classic drop-the-gap-agreement mistake the hunt pipeline must
    classify REPRODUCED."""
    NOOP = -2   # ballot_ring.NOOP
    abs_ = st["base"][:, None, :] + sidx[None, :, None]
    hole = (gap[:, None, :] & (abs_ < frame_slot[:, None, :])
            & ~st["log_commit"] & (st["log_cmd"] == NO_CMD)
            & (abs_ >= st["execute"][:, None, :]))
    return {**st,
            "log_cmd": jnp.where(hole, NOOP, st["log_cmd"]),
            "log_commit": st["log_commit"] | hole}


def recovery_fold(sw, st, p1_win, n_slots: int):
    """Phase-1 win: fold the register file into the winner's own log
    planes BEFORE the P1b merge, so a value committed via the
    in-network vote alone (register is its only durable copy until
    replicas execute past it) is visible to the merge at the switch's
    ballot.  This is the {switch} x recovery quorum intersection —
    skipping it is exactly the lost-fast-commit bug PXQ505 flags."""
    al_vbal = align_to_ring(sw["sw_vbal"], sw["sw_base"], st["base"],
                            n_slots, 0)
    al_vcmd = align_to_ring(sw["sw_vcmd"], sw["sw_base"], st["base"],
                            n_slots, NO_CMD)
    upd = (p1_win[:, None, :] & (al_vbal > st["log_bal"])
           & (al_vbal > 0) & ~st["log_commit"])
    return {**st,
            "log_bal": jnp.where(upd, al_vbal, st["log_bal"]),
            "log_cmd": jnp.where(upd, al_vcmd, st["log_cmd"])}


def evict(sw, execute):
    """Slide the register file past the slowest replica's execute
    frontier (the execution-gated eviction rule: module docstring)."""
    min_exec = jnp.min(execute, axis=0)                # (G,)
    adv = jnp.clip(min_exec - sw["sw_base"], 0, None)
    return dict(sw, sw_base=sw["sw_base"] + adv,
                sw_vbal=shift_window(sw["sw_vbal"], adv, 0),
                sw_vcmd=shift_window(sw["sw_vcmd"], adv, NO_CMD),
                sw_reg_seq=shift_window(sw["sw_reg_seq"], adv, NO_SEQ))
