"""In-fabric consensus tier: switch acceptors + ordered multicast.

A programmable in-network tier the virtual-clock fabric interposes on
the wire ("Paxos Made Switch-y" / "Network Hardware-Accelerated
Consensus" / NOPaxos, PAPERS.md): a ``SwitchAcceptor`` with bounded
register state votes on P2a frames in flight — the leader commits
after ONE fabric delivery instead of a round trip — and a ``Sequencer``
stamps ordered-multicast frames with monotone (session, sequence)
pairs so replicas only DETECT drops (gap-agreement slow path, session
bump on sequencer failover).

Two halves, one contract (pinned by hunt's cross-runtime check):

- ``switchnet/switch.py`` — the host tier ``VirtualClockFabric``
  installs via ``install_switch``;
- ``switchnet/plane.py`` — the same register file as lane-major scan
  carry planes for the ``protocols/switchpaxos`` sim kernel.

See README "In-network consensus" for the commit-path diagrams and
the failover taxonomy.
"""

from paxi_tpu.switchnet.switch import (Sequencer, SwitchAcceptor,
                                       SwitchSnap, SwitchTier,
                                       SwitchVote)

__all__ = ["SwitchAcceptor", "Sequencer", "SwitchTier", "SwitchVote",
           "SwitchSnap"]
