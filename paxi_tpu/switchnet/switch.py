"""Host half of the in-fabric consensus tier: a programmable-switch
acceptor + NOPaxos-style ordered-multicast sequencer the virtual-clock
fabric (host/fabric.py) interposes on the wire.

"Paxos Made Switch-y" / "Network Hardware-Accelerated Consensus"
(PAPERS.md) move acceptor and sequencer logic into the network data
plane; here the data plane IS the fabric's ``submit`` path, so the
tier sees every send mid-flight, exactly where a P4 switch would:

- frames whose class declares ``switchnet_role = "p1a"`` raise the
  switch's ballot promise and trigger a ``SwitchSnap`` register read
  back to the candidate (recovery MUST consult the registers — the
  PXQ505 obligation);
- frames with ``switchnet_role = "p2a"`` are VOTED on in flight
  (bounded ballot/value register file, ``Paxos made switch-y``'s
  acceptor) and STAMPED with a monotone (session, sequence) pair
  (NOPaxos's ordered multicast) — the ``SwitchVote`` injected back to
  the sender arrives after one fabric delivery, which is the
  commit-path round the tier removes;
- everything else passes through untouched.

State is the same bounded register file the sim kernel threads through
its scan carry (switchnet/plane.py — one contract, two runtimes):
``W = sw_window`` slots of (vballot, value, seq) plus the scalar
promise and sequence counter.  Eviction is execution-gated via
``note_execute`` (the replicas report their frontiers on frames they
send; the tier keeps the min), overflow falls back to the replica
majority path, and sequencer churn (down windows + session bumps,
from a Scenario's ``SwitchChurn``) pauses voting/stamping while the
registers and the promise persist.

Determinism: the tier is a pure state machine over the fabric's
submission order — no RNG, no wall clock — so two replays of one
schedule produce byte-identical stamp logs (``stamp_log``), which is
the fabric-level ordered-multicast determinism contract the tests
pin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from paxi_tpu.host.codec import register_message
from paxi_tpu.scenarios.schedule import (switch_down_at,
                                         switch_session_at)

NO_CMD: Any = None   # empty value register (host frames carry batches)
NO_SEQ = -1


@register_message
@dataclass
class SwitchVote:
    """The switch's in-network acceptance of one (ballot, slot) frame,
    injected back to the frame's sender: the leader commits on it
    after ONE fabric delivery.  Carries the ordered-multicast stamp so
    the leader learns its frames' sequence numbers (gap-agreement
    lookups, P3 stamps)."""

    ballot: int
    slot: int
    sess: int = 0
    seq: int = NO_SEQ


@register_message
@dataclass
class SwitchSnap:
    """Register read for recovery, injected back to a phase-1
    candidate: the switch's promise plus every occupied register as
    ``slot -> [vballot, frame payload, seq]``."""

    ballot: int
    base: int = 0
    regs: Dict[int, list] = field(default_factory=dict)


@dataclass
class _Reg:
    vbal: int = 0
    vcmd: Any = NO_CMD
    seq: int = NO_SEQ


class SwitchAcceptor:
    """The bounded acceptor register file (one consensus group)."""

    def __init__(self, window: int):
        self.window = int(window)
        self.bal = 0                      # ballot promise
        self.base = 0                     # abs slot of register 0
        self.regs: List[_Reg] = [_Reg() for _ in range(self.window)]
        self.overflows = 0

    def promise(self, ballot: int) -> None:
        self.bal = max(self.bal, int(ballot))

    def reg_at(self, slot: int) -> Optional[_Reg]:
        rel = slot - self.base
        return self.regs[rel] if 0 <= rel < self.window else None

    def vote(self, ballot: int, slot: int, cmd) -> Optional[_Reg]:
        """Vote on a frame in flight: register (ballot, value) when
        ``ballot`` meets the promise and the slot is in the file.
        Returns the register (the vote) or None (stale ballot, or
        overflow -> the replica fall-back path)."""
        if ballot < self.bal:
            return None
        r = self.reg_at(slot)
        if r is None:
            self.overflows += 1
            return None
        self.bal = ballot
        if ballot >= r.vbal:
            if ballot > r.vbal:
                r.seq = NO_SEQ     # a higher ballot re-stamps
            r.vbal, r.vcmd = ballot, cmd
        return r

    def evict(self, min_execute: int) -> None:
        """Execution-gated eviction: recycle registers only below the
        slowest replica's execute frontier (plane.py contract)."""
        adv = min_execute - self.base
        if adv <= 0:
            return
        if adv >= self.window:
            self.regs = [_Reg() for _ in range(self.window)]
        else:
            self.regs = self.regs[adv:] + [_Reg() for _ in range(adv)]
        self.base = min_execute

    def snapshot(self) -> Dict[int, list]:
        return {self.base + i: [r.vbal, r.vcmd, r.seq]
                for i, r in enumerate(self.regs) if r.vbal > 0}


class Sequencer:
    """Monotone ordered-multicast stamping; the session epoch comes
    from the churn schedule (failover = the standby taking over)."""

    def __init__(self):
        self.next_seq = 0

    def stamp(self, reg: _Reg) -> int:
        """Assign the frame's sequence number, once per registered
        (ballot, slot): a retransmit keeps its original stamp."""
        if reg.seq == NO_SEQ:
            reg.seq = self.next_seq
            self.next_seq += 1
        return reg.seq


class SwitchTier:
    """The fabric interposition: acceptor + sequencer + churn schedule.

    ``churn`` is a Scenario ``SwitchChurn`` (or None for an always-up
    switch).  Install with ``fabric.install_switch(tier)``; the fabric
    calls ``on_send`` for every submission and delivers the returned
    ``(dst, msg)`` injections one logical step out (exactly the sim's
    one-delivery vote visibility)."""

    def __init__(self, window: int = 16, churn=None,
                 n_replicas: Optional[int] = None):
        self.acceptor = SwitchAcceptor(window)
        self.seqr = Sequencer()
        self.churn = churn
        # eviction is min-over-ALL-frontiers: until every replica has
        # gossiped at least once, a partial min could overestimate and
        # evict a register whose slot a silent laggard still needs
        self.n_replicas = n_replicas
        self._exec: Dict[str, int] = {}
        # one register read per (candidate, ballot): a P1a broadcast
        # submits the same frame once per destination edge
        self._snapped: Dict[str, int] = {}
        self.stats = {"votes": 0, "stamps": 0, "snaps": 0,
                      "passed_down": 0}
        # (step, sess, seq, ballot, slot) per stamp — the determinism
        # contract's witness (byte-identical across replays)
        self.stamp_log: List[Tuple[int, int, int, int, int]] = []

    # ---- churn schedule --------------------------------------------------
    def down(self, step: int) -> bool:
        c = self.churn
        return c is not None and switch_down_at(c.start, c.period,
                                                c.down_for, step)

    def session(self, step: int) -> int:
        c = self.churn
        if c is None:
            return 0
        return switch_session_at(c.start, c.period, c.down_for, step)

    # ---- execution-frontier gossip --------------------------------------
    def note_execute(self, src: str, execute: int) -> None:
        self._exec[src] = max(self._exec.get(src, 0), int(execute))
        if self.n_replicas is not None and \
                len(self._exec) < self.n_replicas:
            return
        self.acceptor.evict(min(self._exec.values()))

    # ---- the data plane --------------------------------------------------
    def on_send(self, step: int, src: str, dst: str,
                msg: Any) -> List[Tuple[str, Any]]:
        """One frame passing the switch.  May stamp ``msg`` in place
        (all broadcast copies share the object, so the stamp is
        frame-wide) and returns injections to deliver next step."""
        role = getattr(type(msg), "switchnet_role", None)
        if role is None:
            return []
        ex = getattr(msg, "execute", None)
        if ex is not None:
            self.note_execute(src, ex)
        if role == "p1a":
            self.acceptor.promise(msg.ballot)
            if self._snapped.get(src, -1) >= msg.ballot:
                return []   # same election's other broadcast copies
            self._snapped[src] = msg.ballot
            self.stats["snaps"] += 1
            return [(src, SwitchSnap(self.acceptor.bal,
                                     self.acceptor.base,
                                     self.acceptor.snapshot()))]
        if role != "p2a":
            return []
        if self.down(step):
            self.stats["passed_down"] += 1
            return []
        reg = self.acceptor.vote(msg.ballot, msg.slot,
                                 getattr(msg, "cmds", None))
        if reg is None or reg.vbal != msg.ballot:
            return []   # stale ballot or overflow: pass through unvoted
        first = reg.seq == NO_SEQ
        seq = self.seqr.stamp(reg)
        sess = self.session(step)
        msg.sess, msg.seq = sess, seq
        if not first:
            return []   # a retransmit: stamped, but vote already sent
        self.stats["votes"] += 1
        self.stats["stamps"] += 1
        self.stamp_log.append((step, sess, seq, msg.ballot, msg.slot))
        return [(src, SwitchVote(msg.ballot, msg.slot, sess, seq))]
