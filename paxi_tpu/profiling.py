"""Phase-timed profiling harness for the sim runtime.

``python -m paxi_tpu profile`` answers "where did the wall time go?"
for a bench-shaped run without reading bench.py's artifact plumbing:
it splits the run into the phases that matter for regressions —
trace/lower, XLA compile, first-touch warmup, steady-state execution —
wall-times each, derives per-step and per-slot rates, and (optionally)
wraps the timed run in ``jax.profiler.trace`` so the op-level XLA
profile lands in a TensorBoard/xprof-readable directory.

The timed run reuses the exact executable the warmup compiled (AOT
``lower().compile()``), so a regression in any phase is attributable:
compile_s regressions are kernel-graph growth, warmup_s regressions
are allocator/transfer behavior, run_s regressions are the scan body
itself.  ``steps_per_s`` at two group counts separates per-step
overhead from per-group compute.  Everything stays on device until the
final metric readout — the harness adds no per-step host syncs (that
is the property it exists to police; see ``repeats``).
"""

from __future__ import annotations

import contextlib
import json
import re
import time
from typing import Optional

__all__ = ["run_profile", "gather_report"]

# kernels rewritten onto the fixed-cell layout (PR 15) keep their
# frozen sliding-window counterpart in-tree as ``sim_sw.py`` — both
# for the bit-canonical equivalence proof (tests/test_fixed_cell_equiv)
# and so ``profile --gathers`` can diff the two compiled HLOs and make
# the "shift gathers eliminated" claim checkable from the CLI
SW_TWINS = {
    "paxos": "paxi_tpu.protocols.paxos.sim_sw",
    "sdpaxos": "paxi_tpu.protocols.sdpaxos.sim_sw",
    "wpaxos": "paxi_tpu.protocols.wpaxos.sim_sw",
    "wankeeper": "paxi_tpu.protocols.wankeeper.sim_sw",
    "bpaxos": "paxi_tpu.protocols.bpaxos.sim_sw",
}

# data-movement op families worth watching in the optimized HLO; the
# fixed-cell claim is about ``gather`` (XLA:CPU scalarizes it), the
# others are context
_HLO_OPS = ("gather", "scatter", "dynamic-slice", "dynamic-update-slice")


def hlo_op_counts(compiled) -> dict:
    """Count data-movement ops in a compiled executable's optimized
    HLO.  ``(?<![-\\w])`` keeps collective ops (all-gather) and name
    fragments from inflating the counts."""
    txt = compiled.as_text()
    return {op: len(re.findall(rf"(?<![-\w]){op}\(", txt))
            for op in _HLO_OPS}


def run_profile(algorithm: str = "paxos_pg", groups: int = 2048,
                steps: int = 36, replicas: int = 5, slots: int = 64,
                seed: int = 0, shard: int = 0, repeats: int = 3,
                exchange: str = "dense",
                trace_dir: str = "",
                fuzz=None) -> dict:
    """Run one bench-shaped simulation with per-phase wall timings.

    ``shard`` > 0 builds the run on an N-device mesh
    (parallel/mesh.make_sharded_run); ``repeats`` re-invokes the timed
    executable and reports the best wall (steady state, no compile).
    Returns the report dict (the CLI prints it as one JSON line)."""
    import jax
    import jax.random as jr

    from paxi_tpu.protocols import sim_protocol
    from paxi_tpu.sim import FuzzConfig, SimConfig, make_run

    t0 = time.perf_counter()
    proto = sim_protocol(algorithm)
    cfg = SimConfig(n_replicas=replicas, n_slots=slots)
    fuzz = fuzz or FuzzConfig()
    # the fused exchange exists for lane-major kernels only; report
    # what actually ran so dense-vs-pallas profile diffs can't lie
    if not proto.batched:
        exchange = "dense"
    if shard:
        from paxi_tpu.parallel import make_mesh, make_sharded_run
        mesh = make_mesh(min(shard, len(jax.devices())))
        run = make_sharded_run(proto, cfg, fuzz=fuzz, mesh=mesh,
                               exchange=exchange)
        n_dev = mesh.shape["i"]
    else:
        run = make_run(proto, cfg, fuzz=fuzz, exchange=exchange)
        n_dev = 1
    build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    lowered = run.lower(jr.PRNGKey(seed), groups, steps)
    lower_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    hlo_ops = hlo_op_counts(compiled)

    t0 = time.perf_counter()
    jax.block_until_ready(compiled(jr.PRNGKey(seed + 1)))
    warmup_s = time.perf_counter() - t0

    prof = (jax.profiler.trace(trace_dir) if trace_dir
            else contextlib.nullcontext())
    best = float("inf")
    with prof:
        for _ in range(max(repeats, 1)):
            t0 = time.perf_counter()
            _, metrics, viols = compiled(jr.PRNGKey(seed))
            jax.block_until_ready(viols)
            best = min(best, time.perf_counter() - t0)

    committed = int(metrics.get("committed_slots", 0))
    return {
        "algorithm": algorithm,
        "groups": groups,
        "steps": steps,
        "replicas": replicas,
        "ring_slots": slots,
        "mesh": n_dev if shard else 0,
        "exchange": exchange,
        "device": str(jax.devices()[0]),
        "phases": {
            "build_s": round(build_s, 4),
            "lower_s": round(lower_s, 4),
            "compile_s": round(compile_s, 4),
            "warmup_s": round(warmup_s, 4),
            "run_s": round(best, 4),
        },
        "steps_per_s": round(steps / best, 2),
        "slots_per_s": round(committed / best, 1),
        "committed_slots": committed,
        "invariant_violations": int(viols),
        # data-movement ops in the optimized HLO (hlo_op_counts): the
        # structural half of a wall-time regression diagnosis — a
        # jump in ``gather`` on a fixed-cell kernel means a layout
        # regression (see gather_report / ``profile --gathers``)
        "hlo_ops": hlo_ops,
        "profile_dir": trace_dir or None,
    }


def gather_report(algorithm: str = "paxos", groups: int = 64,
                  steps: int = 16, replicas: int = 5, slots: int = 64,
                  fuzz=None) -> dict:
    """Compile a kernel (small shape — op counts are shape-independent
    structure) and report its data-movement op counts; for the five
    fixed-cell rewrites, also compile the frozen ``sim_sw`` layout twin
    and report the before/after delta — the CLI-checkable form of the
    "per-step ring-shift gathers eliminated" claim.

    ``python -m paxi_tpu profile --gathers [-algorithm X]``."""
    import importlib

    import jax.random as jr

    from paxi_tpu.protocols import sim_protocol
    from paxi_tpu.sim import FuzzConfig, SimConfig, make_run

    cfg = SimConfig(n_replicas=replicas, n_slots=slots)
    fuzz = fuzz or FuzzConfig()

    def compile_counts(proto):
        run = make_run(proto, cfg, fuzz=fuzz)
        return hlo_op_counts(run.lower(jr.PRNGKey(0), groups, steps)
                             .compile())

    out = {
        "algorithm": algorithm,
        "groups": groups,
        "steps": steps,
        "replicas": replicas,
        "ring_slots": slots,
        "hlo_ops": compile_counts(sim_protocol(algorithm)),
    }
    tw = SW_TWINS.get(algorithm)
    if tw is not None:
        sw = importlib.import_module(tw).PROTOCOL
        out["hlo_ops_sw"] = compile_counts(sw)
        out["gathers_eliminated"] = (out["hlo_ops_sw"]["gather"]
                                     - out["hlo_ops"]["gather"])
    return out


def main_json(**kw) -> int:
    if kw.pop("gathers", False):
        kw.pop("seed", None)
        kw.pop("shard", None)
        kw.pop("repeats", None)
        kw.pop("exchange", None)
        kw.pop("trace_dir", None)
        rep = gather_report(**kw)
        print(json.dumps(rep))
        return 0
    rep = run_profile(**kw)
    print(json.dumps(rep))
    return 0 if rep["invariant_violations"] == 0 else 1
