"""Command-line entry points.

Reference: paxi's three binaries [high]:
- ``bin/server``  -> ``python -m paxi_tpu server -id 1.1 -algorithm paxos
  [-simulation]`` (``-simulation`` runs EVERY id from the config in one
  process over the in-process fabric)
- ``bin/client``  -> ``python -m paxi_tpu client`` (closed-loop benchmark
  from the config's benchmark block + linearizability check)
- ``bin/cmd``     -> ``python -m paxi_tpu cmd`` (admin REPL: get/put/
  crash/drop)

Plus the TPU-native runtime the reference doesn't have:
- ``python -m paxi_tpu sim -algorithm paxos -groups 100000 -steps 100``
  (the vmapped/jitted protocol simulator with fuzzing + invariants)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from paxi_tpu.core.config import Bconfig, Config, local_config
from paxi_tpu.core.ident import ID
from paxi_tpu.utils import log


def _load_config(args) -> Config:
    if args.config:
        return Config.from_json(args.config)
    return local_config(args.n, zones=getattr(args, "zones", 1))


def cmd_server(args) -> int:
    cfg = _load_config(args)
    log.configure(args.log_level, args.log_dir, tag=args.id or "sim")
    if args.simulation:
        from paxi_tpu.host.simulation import Cluster
        cfg.addrs = {i: f"chan://sim/{i}" for i in cfg.addrs}

        async def main():
            c = Cluster(args.algorithm, cfg=cfg)
            await c.start()
            log.infof("simulation: %d replicas of %s running",
                      len(cfg.addrs), args.algorithm)
            await asyncio.Event().wait()
        asyncio.run(main())
        return 0
    from paxi_tpu.protocols import host_replica
    replica = host_replica(args.algorithm)(ID(args.id), cfg)
    log.infof("server %s (%s) on %s", args.id, args.algorithm,
              cfg.addrs[ID(args.id)])
    replica.run_forever()
    return 0


def cmd_client(args) -> int:
    cfg = _load_config(args)
    b = cfg.benchmark
    if args.T is not None:
        b.T, b.N = args.T, 0
    if args.N is not None:
        b.T, b.N = 0, args.N
    if args.concurrency:
        b.concurrency = args.concurrency
    from paxi_tpu.host.benchmark import Benchmark
    bench = Benchmark(cfg, b, seed=args.seed)
    stats = asyncio.run(bench.run())
    print(json.dumps(stats.summary()))
    if args.history_file:
        bench.history.write_file(args.history_file)
    if stats.ops == 0 or (stats.anomalies or 0) > 0:
        return 1   # total failure or a safety anomaly
    return 0


def cmd_bench_host(args) -> int:
    """Host-serving benchmark (one protocol through the full stack).

    Default: the closed-loop generator (bench_host.py semantics, one
    protocol).  ``--open-loop``: Poisson arrivals over pipelined
    connections ramped across ``-rates``, reporting the saturation
    curve (offered vs achieved vs latency) + a linearizability verdict
    over the whole run; ``-out`` writes the artifact
    (BENCH_HOST_SATURATION.json).  ``--cluster-proc`` runs the cluster
    in a subprocess so the load generator and the replicas don't share
    one interpreter/GIL — the honest single-node measurement on a
    multi-core box.
    """
    import os
    import subprocess
    import tempfile

    from paxi_tpu.core.config import local_config
    from paxi_tpu.host.transport import wait_listening

    if args.shards:
        # sharded multi-group serving (paxi_tpu/shard/): G groups of
        # fleet/G replicas behind the router, the open-loop ramp in
        # both key-range phases + the 2PC atomicity burst
        from paxi_tpu.shard.bench import shard_ramp
        rates = [float(r) for r in args.rates.split(",") if r]
        out = asyncio.run(shard_ramp(
            algorithm=args.algorithm, shards=args.shards,
            fleet=args.shard_fleet, workers=args.shard_workers,
            rates=rates, step_s=args.step_s, K=args.K, W=args.W,
            seed=args.seed, base_port=args.base_port,
            txns=args.txns, lin=not args.no_lin, conns=args.conns,
            proc=args.cluster_proc,
            workload=getattr(args, "workload", ""),
            migrate=getattr(args, "migrate", False),
            routers=getattr(args, "routers", 1)))
        print(json.dumps({k: v for k, v in out.items()
                          if k != "phases"}))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        txn = out.get("txn") or {}
        bad = ((out["anomalies"] or 0) > 0
               or txn.get("atomicity_violations", 0) > 0
               or all(s["completed"] == 0
                      for p in out["phases"] for s in p["steps"]))
        return 1 if bad else 0

    cfg = _load_config(args)
    if not args.config:
        cfg = local_config(args.n, zones=args.zones,
                           base_port=args.base_port)
    cfg.batch_size = args.batch_size
    cfg.batch_wait = args.batch_wait
    cfg.leader_reads = args.leader_reads
    rates = [float(r) for r in args.rates.split(",") if r]

    if args.trace_sample > 0:
        # head-based sampling at the node HTTP entry; subprocess
        # clusters inherit the rate via PAXI_TRACE_SAMPLE below
        from paxi_tpu.obs import set_sample_rate
        set_sample_rate(args.trace_sample)

    wl = None
    if getattr(args, "workload", ""):
        from paxi_tpu.workload import named_workload
        try:
            wl = named_workload(args.workload)
        except KeyError as e:
            print(f"bench-host: {e.args[0]}", file=sys.stderr)
            return 2

    async def run_open_loop(target_cfg, worker_rates=None):
        from paxi_tpu.host.benchmark import OpenLoopBenchmark
        bench = OpenLoopBenchmark(
            target_cfg, rates=worker_rates or rates, step_s=args.step_s,
            seed=args.seed, conns=args.conns, W=args.W, K=args.K,
            key_base=args.key_base, client_tag=args.client_tag,
            ops_per_req=args.ops_per_req,
            max_inflight=args.max_inflight,
            linearizability_check=not args.no_lin,
            workload=wl, wl_stream=args.wl_stream)
        return await bench.run()

    if args.attach:
        # generator-worker mode: drive an ALREADY-RUNNING cluster over
        # the config's http addrs and print the raw report (the parent
        # merges workers' counts, histograms and verdicts)
        out = asyncio.run(run_open_loop(cfg))
        print(json.dumps(out))
        return 0 if (out.get("anomalies") or 0) == 0 \
            and out["total_completed"] > 0 else 1

    async def scrape_metrics(target_cfg):
        """Leader metrics snapshot over the same REST surface
        (GET /metrics?format=json) — batch/socket counters for the
        artifact without reaching into another process."""
        from paxi_tpu.host.client import _Conn
        conn = _Conn(target_cfg.http_addrs[target_cfg.ids[0]])
        try:
            status, _, payload = await conn.request(
                "GET", "/metrics?format=json", {}, b"")
            return json.loads(payload.decode()) if status == 200 else {}
        except (IOError, OSError):
            return {}
        finally:
            conn.close()

    async def scrape_spans(target_cfg):
        """Every node's GET /spans, merged and reduced to the
        five-phase decomposition (queue/batch/quorum/exec/writeback)
        — the bench-row payload that measures where a command's time
        went instead of inferring it."""
        from paxi_tpu.host.client import Client
        from paxi_tpu.obs import aggregate_phases
        cl = Client(target_cfg)
        try:
            return aggregate_phases(await cl.spans_all())
        finally:
            cl.close()

    def wait_http(url, timeout_s=20.0):
        return asyncio.run(wait_listening(url, timeout_s=timeout_s))

    report = {"protocol": args.algorithm, "replicas": cfg.n,
              "zones": len(cfg.zones()),
              "batch_size": cfg.batch_size,
              "batch_wait": cfg.batch_wait,
              "leader_reads": cfg.leader_reads,
              "ops_per_req": args.ops_per_req,
              **({"workload": wl.name} if wl is not None else {}),
              "cluster_proc": bool(args.cluster_proc
                                   or args.gen_procs > 1)}

    if args.cluster_proc or args.gen_procs > 1:
        # the cluster lives in its own interpreter: chan peers inside
        # that process, real TCP HTTP towards this one
        cfg.addrs = {i: f"chan://benchhost/{i}" for i in cfg.addrs}
        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as f:
            cfg_path = f.name
        cfg.to_json(cfg_path)
        proc = subprocess.Popen(
            [sys.executable, "-m", "paxi_tpu", "server", "-simulation",
             "-algorithm", args.algorithm, "-config", cfg_path],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PAXI_TRACE_SAMPLE": str(args.trace_sample)})
        try:
            if not wait_http(cfg.http_addrs[cfg.ids[0]]):
                print("bench-host: cluster subprocess never came up",
                      file=sys.stderr)
                return 2
            if args.open_loop and args.gen_procs > 1:
                out = _parallel_workers(args, cfg_path, rates)
                out["cluster_metrics"] = asyncio.run(scrape_metrics(cfg))
            elif args.open_loop:
                out = asyncio.run(run_open_loop(cfg))
                out["cluster_metrics"] = asyncio.run(scrape_metrics(cfg))
            else:
                out = asyncio.run(_closed_loop(args, cfg))
            if args.trace_sample > 0:
                out["span_phases"] = asyncio.run(scrape_spans(cfg))
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()     # wedged (e.g. mid-compile): escalate
                proc.wait(timeout=10)
            try:
                os.unlink(cfg_path)
            except OSError:
                pass
        report.update(out)
    else:
        async def inproc():
            from paxi_tpu.host.simulation import Cluster
            cfg.addrs = {i: f"chan://benchhost/{i}" for i in cfg.addrs}
            c = Cluster(args.algorithm, cfg=cfg, http=True)
            await c.start()
            try:
                if args.open_loop:
                    out = await run_open_loop(cfg)
                else:
                    out = await _closed_loop(args, cfg)
                from paxi_tpu.metrics import merge_snapshots
                out["cluster_metrics"] = merge_snapshots(
                    r.metrics.snapshot() for r in c.replicas.values())
                if args.trace_sample > 0:
                    from paxi_tpu.obs import aggregate_phases, merge
                    out["span_phases"] = aggregate_phases(merge(
                        [r.spans.export()
                         for r in c.replicas.values()]))
                return out
            finally:
                await c.stop()
        report.update(asyncio.run(inproc()))

    print(json.dumps({k: v for k, v in report.items()
                      if k != "cluster_metrics"}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    anomalies = report.get("anomalies")
    completed = report.get("total_completed", report.get("ops", 0))
    return 1 if (anomalies or 0) > 0 or completed == 0 else 0


def _parallel_workers(args, cfg_path: str, rates) -> dict:
    """Fan the offered load over ``-gen_procs`` generator subprocesses
    (each rate split evenly; disjoint key ranges + client tags) and
    merge their reports: counts add, per-rate latency histograms
    bucket-merge exactly, per-key-slice linearizability verdicts add."""
    import os
    import subprocess

    from paxi_tpu.metrics import Histogram

    n = args.gen_procs
    worker_rates = [r / n for r in rates]
    procs = []
    for w in range(n):
        cmd = [sys.executable, "-m", "paxi_tpu", "bench-host",
               "--open-loop", "--attach", "-config", cfg_path,
               "-rates", ",".join(str(r) for r in worker_rates),
               "-step_s", str(args.step_s), "-conns", str(args.conns),
               "-W", str(args.W), "-K", str(args.K),
               "-seed", str(args.seed + 1000 * w),
               "-key_base", str(w * args.K),
               "-ops_per_req", str(args.ops_per_req),
               "-max_inflight", str(args.max_inflight),
               "-client_tag", f"w{w}c"]
        if getattr(args, "workload", ""):
            # each worker keeps the spec but draws its own counter
            # stream (deterministic per worker, independent across)
            cmd += ["-workload", args.workload, "-wl_stream", str(w)]
        if args.no_lin:
            cmd.append("--no-lin")
        procs.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}))
    reports = []
    for w, p in enumerate(procs):
        stdout, _ = p.communicate(timeout=600)
        lines = stdout.decode().splitlines()
        if p.returncode != 0 or not lines:
            for q in procs:          # don't leave siblings running
                if q.poll() is None:
                    q.kill()
            raise RuntimeError(
                f"bench-host generator worker {w} failed "
                f"(rc={p.returncode}, {len(lines)} output lines) — "
                f"its stderr was inherited, see above")
        reports.append(json.loads(lines[-1]))

    steps = []
    for i, rate in enumerate(rates):
        merged = {"offered_ops_s": rate, "duration_s": args.step_s}
        for k in ("submitted", "completed", "errors", "shed",
                  "unfinished"):
            merged[k] = sum(r["steps"][i][k] for r in reports)
        merged["achieved_ops_s"] = round(
            sum(r["steps"][i]["achieved_ops_s"] for r in reports), 1)
        h = Histogram()
        by_class: dict = {}
        for r in reports:
            for hs in r["metrics"]["histograms"]:
                if hs["labels"].get("rate") != str(worker_rates[i]):
                    continue
                kc = hs["labels"].get("key_class")
                if kc is None:
                    h.merge(Histogram.from_snapshot(hs))
                else:
                    # workers double-record into a per-key-class series;
                    # keep it out of the overall merge and bucket-merge
                    # per class instead
                    by_class.setdefault(kc, Histogram()).merge(
                        Histogram.from_snapshot(hs))
        merged["latency_ms"] = {
            "mean": round(h.mean() * 1e3, 3),
            "p50": round(h.percentile(50) * 1e3, 3),
            "p95": round(h.percentile(95) * 1e3, 3),
            "p99": round(h.percentile(99) * 1e3, 3),
            "max": round(h.max * 1e3, 3),
        }
        if by_class:
            merged["key_class_latency"] = {
                c: {"n": ch.count,
                    "p50_ms": round(ch.percentile(50) * 1e3, 3),
                    "p99_ms": round(ch.percentile(99) * 1e3, 3)}
                for c, ch in by_class.items()}
        steps.append(merged)
    achieved = [s["achieved_ops_s"] for s in steps]
    peak = max(range(len(steps)), key=lambda i: achieved[i])
    anomalies = None if args.no_lin else sum(
        r["anomalies"] or 0 for r in reports)
    return {
        "mode": "open-loop",
        "gen_procs": n,
        "conns_per_gen": args.conns,
        "W": args.W, "K": args.K,
        "steps": steps,
        "peak_ops_s": achieved[peak],
        "peak_offered_ops_s": steps[peak]["offered_ops_s"],
        "total_completed": sum(s["completed"] for s in steps),
        "total_errors": sum(s["errors"] for s in steps),
        "total_shed": sum(s["shed"] for s in steps),
        "anomalies": anomalies,
        "history_ops": sum(r["history_ops"] for r in reports),
    }


async def _closed_loop(args, cfg) -> dict:
    from paxi_tpu.core.config import Bconfig
    from paxi_tpu.host.benchmark import Benchmark
    cfg.benchmark = Bconfig(T=args.T, K=args.K, W=args.W,
                            concurrency=args.concurrency,
                            warmup=args.warmup,
                            linearizability_check=not args.no_lin)
    wl = None
    if getattr(args, "workload", ""):
        from paxi_tpu.workload import named_workload
        wl = named_workload(args.workload)
    bench = Benchmark(cfg, cfg.benchmark, seed=args.seed, workload=wl)
    stats = await bench.run()
    return dict(stats.summary(), mode="closed-loop")


def cmd_repl(args) -> int:
    """Interactive admin REPL (bin/cmd): get/put/crash/drop/slow/flaky."""
    cfg = _load_config(args)
    from paxi_tpu.host.client import AdminClient, Client

    async def main():
        client = Client(cfg, id=args.id or None, client_id="cmd")
        admin = AdminClient(cfg)
        print("commands: get K | put K V | crash ID T | drop ID1 ID2 T | "
              "slow ID1 ID2 MS T | flaky ID1 ID2 P T | exit")
        loop = asyncio.get_running_loop()
        while True:
            try:
                line = await loop.run_in_executor(None, input, "paxi> ")
            except (EOFError, KeyboardInterrupt):
                break
            parts = line.split()
            if not parts:
                continue
            try:
                op = parts[0]
                if op == "exit":
                    break
                elif op == "get":
                    print((await client.get(int(parts[1]))).decode("latin1"))
                elif op == "put":
                    await client.put(int(parts[1]), parts[2].encode())
                    print("ok")
                elif op == "crash":
                    await admin.crash(parts[1], float(parts[2]))
                    print("ok")
                elif op == "drop":
                    await admin.drop(parts[1], parts[2], float(parts[3]))
                    print("ok")
                elif op == "slow":
                    await admin.slow(parts[1], parts[2], float(parts[3]),
                                     float(parts[4]))
                    print("ok")
                elif op == "flaky":
                    await admin.flaky(parts[1], parts[2], float(parts[3]),
                                      float(parts[4]))
                    print("ok")
                else:
                    print(f"unknown command {op!r}")
            except Exception as e:  # REPL: report, keep going
                print(f"error: {e}")
        client.close()
        admin.close()
    asyncio.run(main())
    return 0


def cmd_sim(args) -> int:
    """The TPU sim runtime: vmapped protocol fuzzing at scale."""
    import contextlib

    from paxi_tpu.sim import FuzzConfig, SimConfig
    from paxi_tpu.protocols import sim_protocol
    proto = sim_protocol(args.algorithm)
    cfg = SimConfig(n_replicas=args.replicas, n_slots=args.slots,
                    n_keys=args.keys, n_zones=args.zones)
    fuzz = FuzzConfig(p_drop=args.p_drop, p_dup=args.p_dup,
                      max_delay=args.max_delay,
                      p_crash=args.p_crash, p_partition=args.p_partition)
    if args.profile:
        # tracing/profiling surface (SURVEY §5): the reference leans on
        # go pprof; here the XLA/TPU profile is first-class — view with
        # tensorboard or xprof
        import jax
        prof = jax.profiler.trace(args.profile)
    else:
        prof = contextlib.nullcontext()
    with prof:
        return _run_sim(args, proto, cfg, fuzz)


def _run_sim(args, proto, cfg, fuzz) -> int:
    from paxi_tpu.sim import simulate
    if args.shard:
        from paxi_tpu.parallel import make_mesh, make_sharded_run
        import jax.random as jr
        run = make_sharded_run(proto, cfg, fuzz=fuzz, mesh=make_mesh())
        state, metrics, viols = run(jr.PRNGKey(args.seed),
                                    args.groups, args.steps)
        out = {k: int(v) for k, v in metrics.items()}
        out["invariant_violations"] = int(viols)
    else:
        res = simulate(proto, cfg, args.groups, args.steps, fuzz=fuzz,
                       seed=args.seed)
        out = {k: int(v) for k, v in res.metrics.items()}
        out["invariant_violations"] = int(res.violations)
    out.update(algorithm=args.algorithm, groups=args.groups,
               steps=args.steps, replicas=args.replicas)
    print(json.dumps(out))
    return 0 if out["invariant_violations"] == 0 else 1


def cmd_profile(args) -> int:
    """Per-phase wall timings for a bench-shaped run (lower/compile/
    warmup/steady-state), optional jax.profiler trace — the regression
    diagnosis surface for the north-star speed work (paxi_tpu/
    profiling.py)."""
    from paxi_tpu.profiling import main_json
    from paxi_tpu.sim import FuzzConfig
    fuzz = FuzzConfig(p_drop=args.p_drop, p_dup=args.p_dup,
                      max_delay=args.max_delay)
    return main_json(algorithm=args.algorithm, groups=args.groups,
                     steps=args.steps, replicas=args.replicas,
                     slots=args.slots, seed=args.seed,
                     shard=args.shard, repeats=args.repeats,
                     exchange=args.exchange, trace_dir=args.trace_dir,
                     gathers=args.gathers, fuzz=fuzz)


def cmd_trace(args) -> int:
    """Trace artifacts: inspect, deterministically replay, minimize,
    and project onto the host runtime (see paxi_tpu/trace/)."""
    from paxi_tpu import trace as tr
    t = tr.load(args.file)
    if args.trace_cmd == "info":
        print(json.dumps(dict(t.meta, steps=t.n_steps,
                              events=t.n_events())))
        return 0
    if args.trace_cmd == "replay":
        r = tr.check_determinism(t) if args.twice else tr.replay(t)
        want = (t.meta.get("replay_state_hash")
                if t.meta.get("shrunk") else
                t.meta.get("capture_state_hash"))
        # counter determinism rides along with the state hash: a replay
        # must reproduce the recorded whole-batch message/fault
        # counters.  Compared over the RECORDED keys, so traces
        # captured before a counter existed (e.g. delay_collisions)
        # still replay clean — new counters ride along unchecked.
        want_counts = t.meta.get("replay_counters"
                                 if t.meta.get("shrunk") else
                                 "capture_counters")
        counts_ok = (want_counts is None
                     or all(r.counters.get(k) == v
                            for k, v in want_counts.items()))
        ok = (r.violations == t.meta.get("group_violations", -1)
              and (want is None or r.state_hash == want)
              and counts_ok)
        print(json.dumps({
            "violations": r.violations,
            "first_violation_step": r.first_violation_step(),
            "state_hash": r.state_hash,
            "counters": r.counters,
            "reproduced": ok,
        }))
        return 0 if ok else 1
    if args.trace_cmd == "shrink":
        mini, stats = tr.shrink(t, max_trials=args.max_trials,
                                log=lambda m: print(f"# {m}",
                                                    flush=True))
        out = args.out or (args.file.removesuffix(".npz") + ".min")
        stats["out"] = tr.save(out, mini)
        print(json.dumps(stats))
        return 0
    if args.trace_cmd == "host":
        from paxi_tpu.core.config import local_config
        from paxi_tpu.trace.host import directives_json, host_directives
        cfg = t.sim_config()
        ids = local_config(cfg.n_replicas, zones=cfg.n_zones).ids
        if args.all:
            # batch mode: this trace's projection coverage under EVERY
            # protocol's TRACE_MSG_MAP (the hunt classifier's
            # mappability comparison) — which protocols could replay
            # this schedule exactly, and what each one loses
            from paxi_tpu.hunt.classify import coverage_of
            from paxi_tpu.protocols import _HOST_MODULES
            from paxi_tpu.trace.host import trace_msg_map
            out = {}
            for proto in sorted(_HOST_MODULES):
                m = trace_msg_map(proto)
                if not m:
                    continue
                out[proto] = coverage_of(t, ids=ids, msg_map=m)
            print(json.dumps({"trace_protocol": t.protocol,
                              "coverage": out}))
            return 0
        dirs, stats = host_directives(t, ids, step_s=args.step_ms / 1e3)
        payload = {"directives": directives_json(dirs), "stats": stats}
        if args.seq:
            from paxi_tpu.trace.host import seq_schedule
            sched, sstats = seq_schedule(t, ids)
            payload["sequenced"] = sched.to_json()
            payload["seq_stats"] = sstats
        print(json.dumps(payload))
        return 0
    raise AssertionError(args.trace_cmd)


def cmd_hunt(args) -> int:
    """The divergence-hunting campaign engine (paxi_tpu/hunt/)."""
    from paxi_tpu.hunt import Campaign

    try:
        camp = Campaign(args.dir,
                        protocols=(args.protocols.split(",")
                                   if args.protocols else None),
                        budget=args.budget, quick=args.quick,
                        shrink_trials=args.shrink_trials,
                        host_replay=not args.no_host,
                        traces_dir=args.traces_dir or None,
                        log=(lambda m: None) if args.quiet else None)
    except (KeyError, ValueError) as e:
        print(f"hunt: {e}", file=sys.stderr)
        return 2
    if args.hunt_cmd == "run":
        rep = camp.run()
        t = rep["summary"]["totals"]
        print(json.dumps(rep["summary"]))
        print(f"hunt: {t['runs']} runs, {t['witnesses']} witnesses "
              f"({t['reproduced']} reproduced, {t['diverged']} diverged, "
              f"{t['unmappable']} unmappable, "
              f"{t['unclassified']} unclassified) -> "
              f"{camp.root}/HUNT_REPORT.md", file=sys.stderr)
        return 2 if t["unclassified"] else 0
    if args.hunt_cmd == "status":
        print(json.dumps(camp.status()))
        return 0
    if args.hunt_cmd == "report":
        rep = camp.write_report()
        print(json.dumps(rep["summary"]))
        return 0
    raise AssertionError(args.hunt_cmd)


def cmd_scenario(args) -> int:
    """The WAN topology / churn / reconfiguration scenario engine
    (paxi_tpu/scenarios): list the named catalog, or run one scenario
    on either runtime — the sim (scenario folded into the capturable
    fault schedule) or the virtual-clock host fabric (scenario
    compiled into a SeqSchedule)."""
    from paxi_tpu import scenarios as scn

    if args.scenario_cmd == "list":
        for name in sorted(scn.NAMED):
            print(json.dumps(scn.describe(scn.NAMED[name])))
        return 0
    assert args.scenario_cmd == "run"
    try:
        scenario = scn.named_scenario(args.scenario)
    except KeyError as e:
        print(f"scenario: {e.args[0]}", file=sys.stderr)
        return 2
    try:
        scenario.validate(args.replicas)
    except ValueError as e:
        print(f"scenario: {e}", file=sys.stderr)
        return 2

    from paxi_tpu.sim import FuzzConfig, SimConfig
    cfg = SimConfig(n_replicas=args.replicas, n_slots=args.slots,
                    n_keys=args.keys, n_zones=args.zones,
                    n_objects=args.objects, locality=args.locality)
    # switchnet events (SwitchChurn) compile into the static sim knobs
    # — and ride into the host replay's scfg, where the protocol's
    # HUNT_FABRIC_SETUP hook builds the matching switch tier
    cfg = scn.apply_switch(cfg, scenario)

    if args.host:
        # host runtime: the Scenario compiles into the virtual-clock
        # fabric's fault surface (standing per-edge WAN latencies +
        # per-step crash sets) and the hunt classifier's replay core
        # drives the cluster under it.  The randomized-fault knobs are
        # sim-only (the fabric replays the deterministic scenario
        # schedule alone) — reject them instead of silently ignoring
        if args.p_drop or args.max_delay > 1:
            print("scenario: -p_drop/-max_delay apply to the sim "
                  "runtime only (the -host fabric runs the scenario's "
                  "deterministic schedule)", file=sys.stderr)
            return 2
        from paxi_tpu.host.simulation import chan_config
        from paxi_tpu.hunt.classify import replay_schedule
        hcfg = chan_config(args.replicas, zones=args.zones,
                           tag="scenario")
        sched = scn.seq_schedule_of(scenario, hcfg.ids, args.steps)
        out = asyncio.run(replay_schedule(args.algorithm, cfg, sched,
                                          cfg=hcfg, seed=args.seed))
        payload = dict(out.to_json(), runtime="host",
                       algorithm=args.algorithm, scenario=scenario.name,
                       steps=args.steps)
        print(json.dumps(payload))
        return 0 if not out.violated else 1

    from paxi_tpu.protocols import sim_protocol
    from paxi_tpu.sim import simulate
    proto = sim_protocol(args.algorithm)
    fuzz = scn.with_scenario(
        FuzzConfig(p_drop=args.p_drop, max_delay=args.max_delay),
        scenario)
    res = simulate(proto, cfg, args.groups, args.steps, fuzz=fuzz,
                   seed=args.seed)
    payload = {k: int(v) for k, v in res.metrics.items()
               if not k.startswith("commit_lat_")}
    payload.update(runtime="sim", algorithm=args.algorithm,
                   scenario=scenario.name, groups=args.groups,
                   steps=args.steps, replicas=args.replicas,
                   invariant_violations=int(res.violations))
    # the zone-latency split (the Cloud paper's headline measurement)
    # in mean lock-step rounds, when the kernel instruments it
    payload.update(scn.latency_split(res.metrics))
    print(json.dumps(payload))
    return 0 if payload["invariant_violations"] == 0 else 1


def cmd_workload(args) -> int:
    """The production workload engine (paxi_tpu/workload): list the
    named spec catalog, or run one spec through the sim runtime and
    report the per-key-class latency split.  (The host runtime serves
    the same specs via ``bench-host -workload`` / the closed-loop
    ``BENCH_HOST_WORKLOAD`` env.)"""
    from paxi_tpu import workload as wlmod

    if args.workload_cmd == "list":
        for name in sorted(wlmod.NAMED):
            print(json.dumps(wlmod.describe(wlmod.NAMED[name],
                                            n_keys=args.keys)))
        return 0
    assert args.workload_cmd == "run"
    try:
        wl = wlmod.named_workload(args.workload)
    except KeyError as e:
        print(f"workload: {e.args[0]}", file=sys.stderr)
        return 2

    from paxi_tpu.protocols import sim_protocol
    from paxi_tpu.sim import FuzzConfig, SimConfig, simulate
    cfg = SimConfig(n_replicas=args.replicas, n_slots=args.slots,
                    n_keys=args.keys, n_zones=args.zones,
                    n_objects=args.objects)
    try:
        cfg = wlmod.apply_workload(cfg, wl)
    except ValueError as e:
        print(f"workload: {e}", file=sys.stderr)
        return 2
    proto = sim_protocol(args.algorithm)
    fuzz = FuzzConfig(p_drop=args.p_drop, max_delay=args.max_delay)
    res = simulate(proto, cfg, args.groups, args.steps, fuzz=fuzz,
                   seed=args.seed)
    payload = {k: int(v) for k, v in res.metrics.items()
               if not k.startswith("commit_lat_")}
    payload.update(runtime="sim", algorithm=args.algorithm,
                   workload=wl.name, groups=args.groups,
                   steps=args.steps, replicas=args.replicas,
                   invariant_violations=int(res.violations))
    lat = res.latency_summary()
    if lat is not None:
        payload["commit_latency"] = {k: lat[k] for k in
                                     ("n", "p50_rounds", "p99_rounds")}
    payload["key_class_latency"] = {
        c: {k: s[k] for k in ("n", "mean_rounds", "p50_rounds",
                              "p99_rounds")}
        for c, s in wlmod.class_split(res.state).items()}
    print(json.dumps(payload))
    return 0 if payload["invariant_violations"] == 0 else 1


def cmd_metrics(args) -> int:
    """Pretty-print a metrics snapshot from either source: scrape a
    live host node's /metrics endpoint, or pull the snapshots embedded
    in a JSON artifact (BENCH_HOST.json, FUZZ_SOAK.json, ...).  With
    ``--series``, run the sim instead and export the per-step counter
    time series (SimResult.counter_series — the ROADMAP metrics
    item)."""
    import urllib.request

    from paxi_tpu.metrics import merge_snapshots, pretty

    if args.series:
        from paxi_tpu.protocols import sim_protocol
        from paxi_tpu.sim import FuzzConfig, SimConfig, simulate
        proto = sim_protocol(args.algorithm)
        cfg = SimConfig(n_replicas=args.replicas)
        fuzz = FuzzConfig(p_drop=args.p_drop, p_dup=args.p_dup,
                          max_delay=args.max_delay)
        res = simulate(proto, cfg, args.groups, args.steps, fuzz=fuzz,
                       seed=args.seed, series=True)
        series = {k: [int(x) for x in v]
                  for k, v in sorted(res.counter_series.items())}
        lat = res.latency_summary()
        if getattr(args, "csv", False):
            # artifact-ready CSV: one row per step, one column per
            # counter; run-level context (incl. the in-kernel
            # commit-latency histogram summary) as '#' header comments
            lines = [f"# algorithm={args.algorithm} groups={args.groups}"
                     f" steps={args.steps}"
                     f" violations={int(res.violations)}"]
            if lat is not None:
                lines.append(
                    f"# commit_latency n={lat['n']}"
                    f" p50_rounds={lat['p50_rounds']}"
                    f" p99_rounds={lat['p99_rounds']}"
                    f" p999_rounds={lat['p999_rounds']}"
                    f" inscan_violations={res.inscan_violations}")
            names = list(series)
            lines.append(",".join(["step"] + names))
            for t in range(args.steps):
                lines.append(",".join(
                    [str(t)] + [str(series[n][t]) for n in names]))
            text = "\n".join(lines) + "\n"
        else:
            doc = {
                "algorithm": args.algorithm,
                "groups": args.groups,
                "steps": args.steps,
                "violations": int(res.violations),
                "series": series,
            }
            if lat is not None:
                doc["commit_latency"] = lat
                doc["inscan_violations"] = res.inscan_violations
            text = json.dumps(doc) + "\n"
        if getattr(args, "out", ""):
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0

    def _find_snapshots(doc, out):
        """Walk a JSON document for metric payloads: registry snapshots
        ({"counters": [...], "histograms": [...]}) and the sim runtime's
        plain counter dicts ({"counters": {name: int}})."""
        if isinstance(doc, dict):
            c = doc.get("counters")
            if isinstance(c, list) or isinstance(doc.get("histograms"),
                                                 list):
                out.append({"counters": c if isinstance(c, list) else [],
                            "histograms": doc.get("histograms", [])})
                return
            if isinstance(c, dict):
                out.append({"counters": [
                    {"name": f"net_{k}", "labels": {}, "value": int(v)}
                    for k, v in c.items()], "histograms": []})
                doc = {k: v for k, v in doc.items() if k != "counters"}
            for v in doc.values():
                _find_snapshots(v, out)
        elif isinstance(doc, list):
            for v in doc:
                _find_snapshots(v, out)

    if args.url:
        base = args.url.rstrip("/")
        if args.raw:
            with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
                sys.stdout.write(r.read().decode())
            return 0
        with urllib.request.urlopen(base + "/metrics?format=json",
                                    timeout=10) as r:
            snaps = [json.load(r)]
    else:
        if not args.file:
            print("metrics: need -url or -file", file=sys.stderr)
            return 2
        with open(args.file) as f:
            doc = json.load(f)
        snaps = []
        _find_snapshots(doc, snaps)
        if not snaps:
            print(f"metrics: no snapshots found in {args.file}",
                  file=sys.stderr)
            return 1
    print(pretty(merge_snapshots(snaps)))
    return 0


def cmd_spans(args) -> int:
    """Span timelines: render (ASCII) or export (Chrome trace-event
    JSON for chrome://tracing / Perfetto).

    Sources: ``-url`` scrapes a node's or the shard router's
    ``GET /spans``; ``-file`` reads a JSON artifact and collects every
    span list inside it (a raw ``[{span}, ...]`` dump, a ``{"spans":
    [...]}`` scrape, or a bench/replay artifact embedding one)."""
    import urllib.request

    from paxi_tpu.obs import (ascii_timeline, chrome_trace, merge,
                              orphans, stitched_traces, validate_spans)

    def _find_spans(doc, out):
        if isinstance(doc, dict):
            s = doc.get("spans")
            if (isinstance(s, list)
                    and all(isinstance(d, dict) and "sid" in d
                            for d in s)):
                out.append(s)
                doc = {k: v for k, v in doc.items() if k != "spans"}
            for v in doc.values():
                _find_spans(v, out)
        elif isinstance(doc, list):
            if doc and all(isinstance(d, dict) and "sid" in d
                           and "trace" in d for d in doc):
                out.append(doc)
            else:
                for v in doc:
                    _find_spans(v, out)

    if args.url:
        base = args.url.rstrip("/")
        with urllib.request.urlopen(base + "/spans", timeout=10) as r:
            lists = [json.load(r)["spans"]]
    else:
        if not args.file:
            print("spans: need -url or -file", file=sys.stderr)
            return 2
        with open(args.file) as f:
            doc = json.load(f)
        lists = []
        _find_spans(doc, lists)
        if not lists:
            print(f"spans: no span lists found in {args.file}",
                  file=sys.stderr)
            return 1
    spans = merge(lists)
    errs = validate_spans(spans)
    if errs:
        print("spans: schema violations:\n  " + "\n  ".join(errs[:20]),
              file=sys.stderr)
        return 1
    if args.spans_cmd == "export":
        text = json.dumps(chrome_trace(spans), indent=1)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text + "\n")
        return 0
    sys.stdout.write(ascii_timeline(spans, width=args.width))
    print(f"{len(spans)} spans, "
          f"{len(stitched_traces(spans))} stitched traces, "
          f"{len(orphans(spans))} orphans")
    return 0


def _git_changed_py(root) -> list:
    """Files for ``lint --changed``: tracked modifications vs HEAD plus
    untracked files, filtered to ``paxi_tpu/*.py`` (the analyzer's
    universe).  Deleted files vanish from the diff listing only once
    unlinked, so drop anything that no longer exists."""
    import subprocess
    from pathlib import Path

    names: list = []
    for cmd in (["git", "diff", "--name-only", "HEAD", "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            out = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, check=True).stdout
        except (OSError, subprocess.CalledProcessError) as e:
            raise ValueError(f"--changed needs a git checkout: {e}")
        names.extend(out.splitlines())
    seen = set()
    changed = []
    for n in names:
        if (n.endswith(".py") and n.startswith("paxi_tpu/")
                and n not in seen and (root / n).is_file()):
            seen.add(n)
            changed.append(Path(root / n))
    return changed


def cmd_lint(args) -> int:
    """paxi-lint: the protocol-aware static analyzer (paxi_tpu/analysis).

    Exits 0 when the tree is clean modulo the checked-in baseline
    (``analysis/baseline.toml``), 1 on violations — cheap enough for
    every commit (pure AST, no jax import)."""
    from pathlib import Path

    from paxi_tpu import analysis

    if args.graph:
        # inspectable analysis coverage: the cross-module call graph
        # the stage-3 rules walk, as DOT (pipe into `dot -Tsvg`)
        from paxi_tpu.analysis.project import shared_index
        print(shared_index(analysis.repo_root()).to_dot())
        return 0

    paths = [Path(p) for p in args.paths]
    strict_targets = False
    if args.changed:
        if paths:
            print("lint: --changed and explicit paths are exclusive",
                  file=sys.stderr)
            return 2
        changed = _git_changed_py(analysis.repo_root())
        if not changed:
            print("lint: no changed paxi_tpu/*.py files — nothing to do")
            return 0
        paths = changed
        # a changed file outside a family's TARGETS globs must stay
        # outside it (same verdicts as a full run, just scoped), so
        # disable the explicit-file escape hatch
        strict_targets = True
    baseline = None if args.no_baseline else (
        Path(args.baseline) if args.baseline else analysis.DEFAULT_BASELINE)
    try:
        report = analysis.run_lint(
            rules=args.rule or None,
            baseline_path=baseline,
            paths=paths or None,
            strict_targets=strict_targets)
    except (KeyError, ValueError) as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2
    if args.sarif:
        text = report.to_sarif()
        if args.sarif == "-":
            print(text)
        else:
            Path(args.sarif).write_text(text + "\n")
    if args.json:
        print(report.to_json())
    else:
        print(report.render(verbose=args.verbose))
    if args.strict_unused and report.unused_baseline:
        # the baseline-shrink policy (scripts/verify.sh --lint): stale
        # suppressions are an error there, a warning in the bare CLI
        print("lint: stale baseline entries (see warnings above) — "
              "baselines may only shrink; delete them",
              file=sys.stderr)
        return 1
    return 0 if report.ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="paxi_tpu",
        description="TPU-native consensus prototyping framework")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("-config", "--config", default="")
        sp.add_argument("-n", type=int, default=3,
                        help="replicas for the default local config")
        sp.add_argument("-zones", "--zones", type=int, default=1)
        # empty default: log.configure falls back to $PAXI_LOG_LEVEL
        sp.add_argument("-log_level", "--log-level", dest="log_level",
                        default="")
        sp.add_argument("-log_dir", "--log-dir", dest="log_dir", default="")

    s = sub.add_parser("server", help="run one replica (or -simulation)")
    common(s)
    s.add_argument("-id", "--id", default="1.1")
    s.add_argument("-algorithm", "--algorithm", default="paxos")
    s.add_argument("-simulation", "--simulation", action="store_true")
    s.set_defaults(fn=cmd_server)

    c = sub.add_parser("client", help="closed-loop benchmark client")
    common(c)
    c.add_argument("-id", "--id", default="")
    c.add_argument("-T", type=int, default=None)
    c.add_argument("-N", type=int, default=None)
    c.add_argument("-concurrency", type=int, default=0)
    c.add_argument("-seed", type=int, default=0)
    c.add_argument("-history_file", "--history-file", default="")
    c.set_defaults(fn=cmd_client)

    bh = sub.add_parser(
        "bench-host",
        help="host-serving benchmark: closed-loop or --open-loop "
             "saturation ramp (BENCH_HOST_SATURATION.json)")
    common(bh)
    bh.add_argument("-algorithm", "--algorithm", default="paxos")
    bh.add_argument("-open_loop", "--open-loop", dest="open_loop",
                    action="store_true",
                    help="Poisson arrivals over pipelined connections, "
                         "ramped across -rates")
    bh.add_argument("-cluster_proc", "--cluster-proc",
                    dest="cluster_proc", action="store_true",
                    help="run the cluster in a subprocess (load "
                         "generator and replicas stop sharing a GIL)")
    bh.add_argument("-rates", "--rates",
                    default="1000,2000,5000,10000,20000,40000,60000",
                    help="comma-separated offered-load ramp (ops/s)")
    bh.add_argument("-step_s", "--step-s", dest="step_s", type=float,
                    default=3.0, help="seconds per rate step")
    bh.add_argument("-conns", "--conns", type=int, default=4,
                    help="pipelined connections (open loop)")
    bh.add_argument("-max_inflight", "--max-inflight",
                    dest="max_inflight", type=int, default=4096,
                    help="open-loop in-flight command cap (beyond it "
                         "arrivals shed, counted)")
    bh.add_argument("-ops_per_req", "--ops-per-req", dest="ops_per_req",
                    type=int, default=1,
                    help="client-side command batching: KV commands "
                         "per HTTP request over the Transaction "
                         "surface (1 = plain per-op REST)")
    bh.add_argument("-T", type=int, default=4,
                    help="closed-loop run seconds")
    bh.add_argument("-concurrency", type=int, default=4)
    bh.add_argument("-warmup", "--warmup", type=float, default=1.0,
                    help="closed-loop warmup window (excluded from "
                         "steady-state ops/s)")
    bh.add_argument("-W", type=float, default=0.5,
                    help="write fraction")
    bh.add_argument("-K", type=int, default=1024,
                    help="key-space size")
    bh.add_argument("-seed", type=int, default=0)
    bh.add_argument("-no_lin", "--no-lin", dest="no_lin",
                    action="store_true",
                    help="skip the linearizability history/check")
    bh.add_argument("-batch_size", "--batch-size", dest="batch_size",
                    type=int, default=64,
                    help="commit-path batch ceiling (cfg.batch_size)")
    bh.add_argument("-batch_wait", "--batch-wait", dest="batch_wait",
                    type=float, default=0.0,
                    help="batch flush-timer ceiling in seconds "
                         "(0 = next event-loop tick)")
    bh.add_argument("-leader_reads", "--leader-reads",
                    dest="leader_reads", action="store_true",
                    help="serve reads at the leader's execute barrier "
                         "instead of log slots (read-index mode; the "
                         "linearizability checker still gates the run)")
    bh.add_argument("-base_port", "--base-port", dest="base_port",
                    type=int, default=1735)
    bh.add_argument("-out", "--out", default="",
                    help="write the full artifact (with cluster "
                         "metrics) to this JSON file")
    bh.add_argument("-gen_procs", "--gen-procs", dest="gen_procs",
                    type=int, default=1,
                    help="parallel generator subprocesses (load and "
                         "key space split evenly; implies "
                         "--cluster-proc)")
    bh.add_argument("-attach", "--attach", action="store_true",
                    help="generator-worker mode: drive an already-"
                         "running cluster (used by -gen-procs)")
    bh.add_argument("-key_base", "--key-base", dest="key_base",
                    type=int, default=0, help="key-range offset")
    bh.add_argument("-client_tag", "--client-tag", dest="client_tag",
                    default="ol", help="client-id prefix")
    bh.add_argument("-workload", "--workload", default="",
                    help="drive the ramp with a named paxi_tpu/workload "
                         "spec (zipf99, flash, hotrange, ...) instead of "
                         "uniform keys")
    bh.add_argument("-wl_stream", "--wl-stream", dest="wl_stream",
                    type=int, default=0,
                    help="workload sampler stream id (parallel workers "
                         "get distinct streams automatically)")
    bh.add_argument("-shards", "--shards", type=int, default=0,
                    help="sharded mode: run G consensus groups of "
                         "shard_fleet/G replicas behind the shard "
                         "router and ramp the open loop against the "
                         "router endpoint (paxi_tpu/shard/)")
    bh.add_argument("-shard_fleet", "--shard-fleet",
                    dest="shard_fleet", type=int, default=12,
                    help="total replicas partitioned over --shards "
                         "groups")
    bh.add_argument("-shard_workers", "--shard-workers",
                    dest="shard_workers", type=int, default=4,
                    help="parallel open-loop generator workers "
                         "(disjoint-then-crossing key ranges)")
    bh.add_argument("-txns", "--txns", type=int, default=8,
                    help="cross-shard 2PC transactions fired after "
                         "the ramp (atomicity oracle)")
    bh.add_argument("-migrate", "--migrate", action="store_true",
                    help="sharded mode: add a live-migration phase — "
                         "hot-range traffic, a mid-phase Rebalancer "
                         "split + streamed NON-EMPTY range move, and "
                         "the migration_blip_p99_ms / readback-oracle "
                         "evidence (shard/migrate.py)")
    bh.add_argument("-routers", "--routers", type=int, default=1,
                    help="sharded mode: router endpoints over the same "
                         "groups (1 primary + N-1 stateless "
                         "secondaries sharing the versioned map)")
    bh.add_argument("-trace_sample", "--trace-sample",
                    dest="trace_sample", type=float, default=0.0,
                    help="span sampling rate 0..1 (0 = tracing off); "
                         "adds the five-phase latency decomposition "
                         "(span_phases) to the artifact")
    bh.set_defaults(fn=cmd_bench_host)

    r = sub.add_parser("cmd", help="admin REPL")
    common(r)
    r.add_argument("-id", "--id", default="")
    r.set_defaults(fn=cmd_repl)

    m = sub.add_parser("sim", help="TPU sim runtime (vmapped fuzzing)")
    m.add_argument("-algorithm", "--algorithm", default="paxos")
    m.add_argument("-groups", type=int, default=1024)
    m.add_argument("-steps", type=int, default=100)
    m.add_argument("-replicas", type=int, default=3)
    m.add_argument("-slots", type=int, default=128)
    m.add_argument("-keys", type=int, default=16)
    m.add_argument("-zones", type=int, default=1)
    m.add_argument("-seed", type=int, default=0)
    m.add_argument("-p_drop", type=float, default=0.0)
    m.add_argument("-p_dup", type=float, default=0.0)
    m.add_argument("-p_crash", type=float, default=0.0)
    m.add_argument("-p_partition", type=float, default=0.0)
    m.add_argument("-max_delay", type=int, default=1)
    m.add_argument("-shard", action="store_true",
                   help="shard groups over the device mesh")
    m.add_argument("-profile", "--profile", default="",
                   help="write a JAX/XLA profiler trace to this dir")
    m.set_defaults(fn=cmd_sim)

    pr = sub.add_parser("profile",
                        help="per-phase wall timings (lower/compile/"
                             "warmup/run) + optional XLA profile")
    pr.add_argument("-algorithm", "--algorithm", default="paxos_pg")
    pr.add_argument("-groups", type=int, default=2048)
    pr.add_argument("-steps", type=int, default=36)
    pr.add_argument("-replicas", type=int, default=5)
    pr.add_argument("-slots", type=int, default=64)
    pr.add_argument("-seed", type=int, default=0)
    pr.add_argument("-shard", type=int, default=0, metavar="N",
                    help="profile on an N-device mesh (0 = single)")
    pr.add_argument("-repeats", type=int, default=3,
                    help="timed re-invocations; best wall reported")
    pr.add_argument("-exchange", choices=("dense", "pallas"),
                    default="dense",
                    help="message-exchange backend (lane-major only)")
    pr.add_argument("-p_drop", type=float, default=0.0)
    pr.add_argument("-p_dup", type=float, default=0.0)
    pr.add_argument("-max_delay", type=int, default=1)
    pr.add_argument("-trace_dir", "-trace-dir", "--trace-dir",
                    dest="trace_dir", default="",
                    help="also write a jax.profiler trace here "
                         "(view with tensorboard/xprof)")
    pr.add_argument("-gathers", "--gathers", action="store_true",
                    help="skip the timed run; report compiled-HLO "
                         "data-movement op counts instead — for the "
                         "five fixed-cell kernels also compiles the "
                         "frozen sim_sw layout twin and prints the "
                         "gathers-eliminated delta")
    pr.set_defaults(fn=cmd_profile)

    t = sub.add_parser("trace", help="violation traces: replay/shrink")
    tsub = t.add_subparsers(dest="trace_cmd", required=True)
    ti = tsub.add_parser("info", help="print a trace's provenance")
    ti.add_argument("file")
    tre = tsub.add_parser("replay",
                          help="pinned deterministic replay in the sim")
    tre.add_argument("file")
    tre.add_argument("-twice", "--twice", action="store_true",
                     help="replay twice and assert identical outcomes")
    tsh = tsub.add_parser("shrink", help="delta-debug a minimal witness")
    tsh.add_argument("file")
    tsh.add_argument("-o", "--out", default="")
    tsh.add_argument("-max_trials", "--max-trials", dest="max_trials",
                     type=int, default=200)
    tho = tsub.add_parser("host",
                          help="project onto host fault directives")
    tho.add_argument("file")
    tho.add_argument("-step_ms", "--step-ms", dest="step_ms",
                     type=float, default=50.0)
    tho.add_argument("-seq", "--seq", action="store_true",
                     help="also emit the sequenced (virtual-clock) "
                          "delivery schedule")
    tho.add_argument("-all", "--all", action="store_true",
                     help="batch mode: projection coverage under every "
                          "protocol's TRACE_MSG_MAP")
    t.set_defaults(fn=cmd_trace)

    h = sub.add_parser("hunt",
                       help="divergence-hunting campaigns (sim->host)")
    hsub = h.add_subparsers(dest="hunt_cmd", required=True)
    for name, desc in (("run", "run/resume a campaign"),
                       ("status", "print campaign progress"),
                       ("report", "regenerate HUNT_REPORT.json/.md")):
        hp = hsub.add_parser(name, help=desc)
        hp.add_argument("-dir", "--dir", default="hunt",
                        help="campaign directory (state + corpus + "
                             "reports)")
        hp.add_argument("-budget", "--budget", type=int, default=5,
                        help="fuzz runs per protocol")
        hp.add_argument("-protocols", "--protocols", default="",
                        help="comma-separated subset (default: every "
                             "mapped protocol)")
        hp.add_argument("-quick", "--quick", action="store_true",
                        help="cap groups/steps for smoke budgets")
        hp.add_argument("-shrink_trials", "--shrink-trials",
                        dest="shrink_trials", type=int, default=120)
        hp.add_argument("-no_host", "--no-host", dest="no_host",
                        action="store_true",
                        help="skip host replay (coverage-only verdicts)")
        hp.add_argument("-traces_dir", "--traces-dir",
                        dest="traces_dir", default="",
                        help="seed corpus from this trace dir on first "
                             "run (default: repo traces/)")
        hp.add_argument("-quiet", "--quiet", action="store_true")
    h.set_defaults(fn=cmd_hunt)

    sc = sub.add_parser("scenario",
                        help="WAN topology / churn / reconfig scenario "
                             "engine (paxi_tpu/scenarios)")
    scsub = sc.add_subparsers(dest="scenario_cmd", required=True)
    scsub.add_parser("list", help="print the named-scenario catalog")
    scr = scsub.add_parser("run",
                           help="run one named scenario on the sim or "
                                "(-host) the virtual-clock fabric")
    scr.add_argument("-scenario", "--scenario", default="wan3z",
                     help="a name from `scenario list`")
    scr.add_argument("-algorithm", "--algorithm", default="wpaxos")
    scr.add_argument("-host", "--host", action="store_true",
                     help="drive the asyncio cluster on the "
                          "virtual-clock fabric instead of the sim")
    scr.add_argument("-groups", type=int, default=16)
    scr.add_argument("-steps", type=int, default=120)
    scr.add_argument("-replicas", type=int, default=9)
    scr.add_argument("-zones", type=int, default=3)
    scr.add_argument("-slots", type=int, default=16)
    scr.add_argument("-keys", type=int, default=16)
    scr.add_argument("-objects", type=int, default=6)
    scr.add_argument("-locality", type=float, default=0.8)
    scr.add_argument("-seed", type=int, default=0)
    scr.add_argument("-p_drop", type=float, default=0.0)
    scr.add_argument("-max_delay", type=int, default=1)
    sc.set_defaults(fn=cmd_scenario)

    wp = sub.add_parser("workload",
                        help="production workload engine: key skew, "
                             "read mixes, flash crowds "
                             "(paxi_tpu/workload)")
    wpsub = wp.add_subparsers(dest="workload_cmd", required=True)
    wpl = wpsub.add_parser("list", help="print the named-spec catalog")
    wpl.add_argument("-keys", type=int, default=64,
                     help="key-space size the descriptions assume")
    wpr = wpsub.add_parser("run",
                           help="run one named spec on the sim runtime")
    wpr.add_argument("-workload", "--workload", default="zipf99",
                     help="a name from `workload list`")
    wpr.add_argument("-algorithm", "--algorithm", default="paxos")
    wpr.add_argument("-groups", type=int, default=16)
    wpr.add_argument("-steps", type=int, default=120)
    wpr.add_argument("-replicas", type=int, default=3)
    wpr.add_argument("-zones", type=int, default=1)
    wpr.add_argument("-slots", type=int, default=16)
    wpr.add_argument("-keys", type=int, default=64)
    wpr.add_argument("-objects", type=int, default=8)
    wpr.add_argument("-seed", type=int, default=0)
    wpr.add_argument("-p_drop", type=float, default=0.0)
    wpr.add_argument("-max_delay", type=int, default=1)
    wp.set_defaults(fn=cmd_workload)

    li = sub.add_parser(
        "lint", help="protocol-aware static analysis (paxi-lint)")
    li.add_argument("paths", nargs="*", default=[],
                    help="restrict to these files/directories "
                         "(default: whole repo)")
    li.add_argument("-rule", "--rule", action="append", default=[],
                    help="run only these rule families: names "
                         "(`quorum-safety`) or code prefixes "
                         "(`PXQ,PXB`); repeatable")
    li.add_argument("-json", "--json", action="store_true",
                    help="machine-readable report")
    li.add_argument("-verbose", "--verbose", action="store_true",
                    help="also list suppressed findings")
    li.add_argument("-baseline", "--baseline", default="",
                    help="alternate baseline file")
    li.add_argument("-no_baseline", "--no-baseline", dest="no_baseline",
                    action="store_true",
                    help="ignore the baseline (show every finding)")
    li.add_argument("-strict_unused", "--strict-unused",
                    dest="strict_unused", action="store_true",
                    help="exit 1 on stale (unused) baseline entries — "
                         "the verify.sh --lint gate's baseline-shrink "
                         "policy")
    li.add_argument("-sarif", "--sarif", default="",
                    help="also write the report as SARIF 2.1.0 to this "
                         "path (`-` for stdout) — CI code-scanning "
                         "upload format")
    li.add_argument("-changed", "--changed", action="store_true",
                    help="lint only paxi_tpu/*.py files changed vs git "
                         "HEAD (plus untracked); families keep their "
                         "TARGETS scoping so verdicts agree with a "
                         "full run")
    li.add_argument("-graph", "--graph", action="store_true",
                    help="dump the ProjectIndex cross-module call "
                         "graph as GraphViz DOT (nodes colored by "
                         "package) instead of linting")
    li.set_defaults(fn=cmd_lint)

    me = sub.add_parser("metrics",
                        help="pretty-print metrics (live node or artifact)")
    me.add_argument("-url", "--url", default="",
                    help="a node's HTTP base, e.g. http://127.0.0.1:2735")
    me.add_argument("-file", "--file", default="",
                    help="a JSON artifact with embedded snapshots")
    me.add_argument("-raw", "--raw", action="store_true",
                    help="with -url: dump the Prometheus text unparsed")
    me.add_argument("-series", "--series", action="store_true",
                    help="run the sim and export the per-step counter "
                         "time series instead")
    me.add_argument("-csv", "--csv", action="store_true",
                    help="with -series: emit CSV (one row per step, "
                         "one column per counter; run-level "
                         "latency-histogram summary in '#' header "
                         "comments) instead of JSON")
    me.add_argument("-out", "--out", default="",
                    help="write the -series export to this file "
                         "instead of stdout")
    me.add_argument("-algorithm", "--algorithm", default="paxos")
    me.add_argument("-groups", type=int, default=64)
    me.add_argument("-steps", type=int, default=100)
    me.add_argument("-replicas", type=int, default=3)
    me.add_argument("-seed", type=int, default=0)
    me.add_argument("-p_drop", type=float, default=0.0)
    me.add_argument("-p_dup", type=float, default=0.0)
    me.add_argument("-max_delay", type=int, default=1)
    me.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser("spans",
                        help="span timelines: ASCII render or Chrome "
                             "trace-event export (paxi_tpu/obs)")
    spsub = sp.add_subparsers(dest="spans_cmd", required=True)
    for name, desc in (("render", "ASCII timeline per trace"),
                       ("export", "Chrome trace-event JSON "
                                  "(chrome://tracing / Perfetto)")):
        ssp = spsub.add_parser(name, help=desc)
        ssp.add_argument("-url", "--url", default="",
                         help="a node's or the shard router's HTTP "
                              "base (scrapes GET /spans)")
        ssp.add_argument("-file", "--file", default="",
                         help="a JSON artifact with embedded span "
                              "lists (scrape dump, bench artifact)")
        ssp.add_argument("-out", "--out", default="",
                         help="write output here instead of stdout")
        ssp.add_argument("-width", "--width", type=int, default=48,
                         help="render: bar width in characters")
    sp.set_defaults(fn=cmd_spans)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
