import sys

from paxi_tpu.cli import main

sys.exit(main())
