"""Host-runtime benchmark sweep: every protocol through the real
deployment stack (in-proc cluster + HTTP client + closed-loop
benchmark + linearizability check) — the reference's primary user
surface (bin/client against a -simulation cluster).

Prints ONE JSON line per protocol and writes the collected list to
BENCH_HOST.json next to this file.  ``anomalies`` is the
linearizability checker's count: 0 expected for every protocol except
the eventually-consistent ones (dynamo, blockchain), whose lines are
labeled ``consistency: eventual`` and run without the check — flagging
them would be testing the wrong promise.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from paxi_tpu.core.config import Bconfig, local_config
from paxi_tpu.host.benchmark import Benchmark
from paxi_tpu.host.simulation import Cluster
from paxi_tpu.metrics import merge_snapshots
from paxi_tpu.workload import named_workload

CONFIGS = [
    # (protocol, n, zones, linearizable?)
    ("paxos", 3, 1, True),
    ("epaxos", 5, 1, True),
    ("wpaxos", 6, 2, True),
    ("abd", 5, 1, True),
    ("chain", 3, 1, True),
    ("kpaxos", 3, 1, True),
    ("sdpaxos", 3, 1, True),
    ("wankeeper", 6, 2, True),
    ("dynamo", 3, 1, False),
    ("blockchain", 3, 1, False),
    # the in-fabric consensus tier's host replica (PR 12): with no
    # switch on the wire it serves as classic paxos over the same
    # frames — this row is the software-path control for the
    # switchpaxos open-loop ramp in BENCH_HOST_SATURATION.json
    ("switchpaxos", 3, 1, True),
]


async def bench_one(name: str, n: int, zones: int, lin: bool) -> dict:
    cfg = local_config(n, zones=zones)
    secs = int(os.environ.get("BENCH_HOST_T", "4"))
    # warmup window excluded from the reported ops/s (PR 6's
    # compile_s/warmup_s split, host flavor): dial-up + leader election
    # don't dilute steady state
    warm = float(os.environ.get("BENCH_HOST_WARMUP", "1.0"))
    cfg.benchmark = Bconfig(T=secs, K=8, W=0.5, concurrency=4,
                            warmup=min(warm, secs / 2),
                            linearizability_check=lin)
    # BENCH_HOST_WORKLOAD=<named spec>: drive every protocol with a
    # paxi_tpu/workload spec instead of the uniform KeyGen/W draws
    # (same spec family the sim kernels compile — workload/compile.py)
    wl_name = os.environ.get("BENCH_HOST_WORKLOAD", "")
    wl = named_workload(wl_name) if wl_name else None
    c = Cluster(name, cfg=cfg, http=True)
    await c.start()
    try:
        t0 = time.perf_counter()
        bench = Benchmark(cfg, cfg.benchmark, seed=1, workload=wl)
        stats = await bench.run()
        dt = time.perf_counter() - t0
        return {
            "metric": f"{name}_host_ops_per_sec",
            # steady-state: completions inside the warmup window are
            # excluded from numerator AND denominator
            "value": round(stats.ops / max(stats.duration - stats.warmup_s,
                                           1e-9), 1),
            "unit": "ops/s",
            "protocol": name,
            "replicas": n,
            "zones": zones,
            "ops": stats.ops,
            "warmup_s": stats.warmup_s,
            "warmup_ops": stats.warmup_ops,
            "errors": stats.errors,
            "anomalies": (stats.anomalies if lin else None),
            "consistency": ("linearizable" if lin else "eventual"),
            **({"workload": wl.name} if wl is not None else {}),
            "wall_s": round(dt, 2),
            "latency": {k: v for k, v in stats.summary().items()
                        if k.startswith("latency_")},
            # the per-message-class evidence (paxi_tpu/metrics/): the
            # bench registry (per-stream op latency histograms + client
            # retries) and the node registries merged cluster-wide
            "metrics": {
                "bench": bench.metrics.snapshot(),
                "cluster": merge_snapshots(
                    r.metrics.snapshot() for r in c.replicas.values()),
            },
        }
    finally:
        await c.stop()


def main() -> int:
    results = []
    worst = 0
    for name, n, zones, lin in CONFIGS:
        try:
            r = asyncio.run(bench_one(name, n, zones, lin))
        except Exception as e:                      # noqa: BLE001
            r = {"metric": f"{name}_host_ops_per_sec", "value": 0,
                 "protocol": name, "error": f"{type(e).__name__}: {e}"}
            worst = 1
        if r.get("errors") or (r.get("anomalies") or 0) > 0:
            worst = 1
        print(json.dumps(r), flush=True)
        results.append(r)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_HOST.json")
    with open(path, "w") as f:
        json.dump(results, f, indent=1)
    return worst


if __name__ == "__main__":
    raise SystemExit(main())
