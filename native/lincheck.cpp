// Native linearizability checker for paxi_tpu's host runtime.
//
// Mirrors paxi_tpu/host/history.py check_key()/_find_cycle_read()
// exactly (reference: paxi history.go / linearizability.go — precedence
// graph over one key's ops: real-time order + read-from data order +
// closure rules, anomalies counted by removing one offending read per
// detected cycle).  Row-major bitset adjacency, Warshall closure in
// n^3/64 word ops; called from Python via ctypes (host/history.py picks
// this over the pure-Python path when the library is built).
//
// Per-op encoding (one key's operations, arrays of length n):
//   is_read[i] : 1 if read
//   val[i]     : written-value id for writes; read-value id for reads;
//                EMPTY_VAL (-2) for a read returning the initial value
//   start[i], end[i] : real-time interval (end may be +inf for open ops)

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int64_t EMPTY_VAL = -2;

struct Bitset {
    std::vector<uint64_t> w;
    explicit Bitset(int n) : w((n + 63) / 64, 0) {}
    void set(int i) { w[i >> 6] |= (1ull << (i & 63)); }
    bool get(int i) const { return (w[i >> 6] >> (i & 63)) & 1; }
    void orWith(const Bitset& o) {
        for (size_t k = 0; k < w.size(); ++k) w[k] |= o.w[k];
    }
    bool intersects(const Bitset& o) const {
        for (size_t k = 0; k < w.size(); ++k)
            if (w[k] & o.w[k]) return true;
        return false;
    }
};

// Warshall transitive closure over bitset rows.
void closure(std::vector<Bitset>& reach, int n) {
    for (int k = 0; k < n; ++k) {
        const Bitset& rk = reach[k];
        for (int i = 0; i < n; ++i) {
            if (reach[i].get(k)) reach[i].orWith(rk);
        }
    }
}

// Returns the index of a read on a cycle (preferring reads), the index
// of any cycle node otherwise, or -1 if linearizable.
int find_cycle_read(const int32_t* is_read, const int64_t* val,
                    const double* start, const double* end,
                    const std::vector<int>& alive) {
    const int n = static_cast<int>(alive.size());
    if (n == 0) return -1;

    std::vector<Bitset> adj(n, Bitset(n));
    std::vector<int> writes;
    for (int i = 0; i < n; ++i)
        if (!is_read[alive[i]]) writes.push_back(i);

    // real-time precedence
    for (int i = 0; i < n; ++i) {
        const double ei = end[alive[i]];
        for (int j = 0; j < n; ++j)
            if (i != j && ei < start[alive[j]]) adj[i].set(j);
    }

    // read-from edges; a non-empty read of a never-written value is
    // itself an anomaly; an empty (initial-value) read precedes every
    // write (lost-update detection)
    std::vector<int> read_from(n, -1);
    for (int i = 0; i < n; ++i) {
        if (!is_read[alive[i]]) continue;
        const int64_t v = val[alive[i]];
        if (v == EMPTY_VAL) {
            for (int w : writes) adj[i].set(w);
            continue;
        }
        int w = -1;
        for (int j : writes)
            if (val[alive[j]] == v) { w = j; }
        if (w < 0) return alive[i];
        adj[w].set(i);
        read_from[i] = w;
    }

    // closure fixpoint with the two data-order rules per read r of w:
    //  (a) any write reaching r precedes w; (b) r precedes any write
    //  that w reaches
    while (true) {
        std::vector<Bitset> reach = adj;
        closure(reach, n);
        bool changed = false;
        for (int r = 0; r < n; ++r) {
            const int w = read_from[r];
            if (w < 0) continue;
            for (int w2 : writes) {
                if (w2 == w) continue;
                if (reach[w2].get(r) && !adj[w2].get(w)) {
                    adj[w2].set(w);
                    changed = true;
                }
                if (reach[w].get(w2) && r != w2 && !adj[r].get(w2)) {
                    adj[r].set(w2);
                    changed = true;
                }
            }
        }
        if (!changed) break;
    }

    std::vector<Bitset> reach = adj;
    closure(reach, n);
    int any = -1;
    for (int i = 0; i < n; ++i) {
        if (reach[i].get(i)) {
            if (is_read[alive[i]]) return alive[i];
            if (any < 0) any = alive[i];
        }
    }
    return any;
}

}  // namespace

extern "C" {

// Anomalous-op count for one key's history (python check_key parity).
int32_t lincheck_key(const int32_t* is_read, const int64_t* val,
                     const double* start, const double* end, int32_t n) {
    std::vector<int> alive(n);
    for (int i = 0; i < n; ++i) alive[i] = i;
    std::vector<char> removed(n, 0);
    int32_t anomalies = 0;
    while (true) {
        int bad = find_cycle_read(is_read, val, start, end, alive);
        if (bad < 0) return anomalies;
        ++anomalies;
        removed[bad] = 1;
        alive.clear();
        for (int i = 0; i < n; ++i)
            if (!removed[i]) alive.push_back(i);
    }
}

int32_t lincheck_version() { return 1; }

}  // extern "C"
