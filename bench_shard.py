"""The shard-count curve: one fixed fleet, G in {1, 2, 4} groups
behind the router, same-day same-box — aggregate cmds/s vs shards
(paxi_tpu/shard/bench.py has the methodology).  G=1 is the control:
the identical fleet, surface, workers and offered ramp, serving as ONE
consensus group.

Every G >= 2 run also performs a live mid-ramp ``move_range`` of a
non-empty hot range (migrate=True): the migrated-keys readback oracle
must be clean and the in-window completion p99 ("migration blip") must
stay within 3x the steady-state p99.

Writes BENCH_SHARD.json; exits nonzero if any run reports
linearizability anomalies, a 2PC atomicity violation, a migration
oracle failure, a blip beyond the 3x gate, or the G=4 aggregate fails
to clear the same-day G=1 control.
"""

from __future__ import annotations

import asyncio
import json
import os
import platform
import time

from paxi_tpu.shard.bench import shard_ramp

GS = (1, 2, 4)
BLIP_GATE = 3.0  # migration blip p99 must stay within 3x steady p99


def _migration_gate(r: dict) -> tuple[dict | None, bool]:
    """(migration block, gate ok) for one shard_ramp result."""
    mig = next((p for p in r["phases"] if p["phase"] == "migrate"),
               None)
    if mig is None:
        return None, True
    m = mig["migration"]
    ok = (m["epoch"] == "complete"
          and (m["installed"] or 0) > 0
          and m["oracle"]["clean"]
          and (mig["anomalies"] or 0) == 0)
    if m["steady_p99_ms"] and m["blip_ratio"] is not None:
        ok = ok and m["blip_ratio"] <= BLIP_GATE
    return m, ok


def main() -> int:
    fleet = int(os.environ.get("BENCH_SHARD_FLEET", "12"))
    workers = int(os.environ.get("BENCH_SHARD_WORKERS", "4"))
    step_s = float(os.environ.get("BENCH_SHARD_STEP_S", "3.0"))
    rates = [float(r) for r in os.environ.get(
        "BENCH_SHARD_RATES", "6000,12000,20000,30000").split(",")]
    curve = []
    worst = 0
    for gi, g in enumerate(GS):
        r = asyncio.run(shard_ramp(
            shards=g, fleet=fleet, workers=workers, rates=rates,
            step_s=step_s, base_port=18300 + 40 * gi,
            migrate=g >= 2))
        print(json.dumps({k: v for k, v in r.items()
                          if k != "phases"}), flush=True)
        curve.append(r)
        if (r["anomalies"] or 0) > 0 or (
                r["txn"] and r["txn"]["atomicity_violations"] > 0):
            worst = 1
        m, ok = _migration_gate(r)
        if m is not None:
            print(json.dumps({"shards": g, "migration": {
                "installed": m["installed"],
                "migration_blip_p99_ms": m["migration_blip_p99_ms"],
                "steady_p99_ms": m["steady_p99_ms"],
                "blip_ratio": m["blip_ratio"],
                "oracle_clean": m["oracle"]["clean"],
                "gate_ok": ok}}), flush=True)
        if not ok:
            worst = 1
    control = next(r for r in curve if r["shards"] == 1)
    top = next(r for r in curve if r["shards"] == GS[-1])
    scaled = top["aggregate_peak_ops_s"] > control["aggregate_peak_ops_s"]
    if not scaled:
        worst = 1
    doc = {
        "description":
            "Aggregate cmds/s vs shard count over a FIXED fleet of "
            f"{fleet} replicas partitioned into G consensus groups "
            "behind one shard-router endpoint (python bench_shard.py; "
            "paxi_tpu/shard/). Same day, same box, same workers/ramp "
            "for every G; G=1 is the control. Each run: disjoint-then-"
            "crossing worker key ranges, per-worker linearizability "
            "verdicts (anomalies sum), and a cross-shard 2PC burst "
            "with a linearizable-readback atomicity oracle; G >= 2 "
            "runs add a live mid-ramp move_range of a non-empty hot "
            "range gated on a clean migrated-keys readback oracle and "
            f"a blip p99 within {BLIP_GATE}x steady p99. The "
            "leader's O(n-1) replication fan shrinks with G — the "
            "compartmentalization papers' bottleneck-role scaling, "
            "observable end-to-end; this box is single-core, so the "
            "win is per-command replication work, not parallelism.",
        "date": time.strftime("%Y-%m-%d"),
        "box": {"platform": platform.platform(),
                "cpus": os.cpu_count()},
        "fleet": fleet,
        "workers": workers,
        "offered_rates_ops_s": rates,
        "curve": curve,
        "g4_above_g1_control": scaled,
        "migration_blip_gate_x": BLIP_GATE,
        "migration_gates_ok": all(
            _migration_gate(r)[1] for r in curve),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_SHARD.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "aggregate_peak_ops_s":
            {str(r["shards"]): r["aggregate_peak_ops_s"]
             for r in curve},
        "g4_above_g1_control": scaled,
        "anomalies": sum(r["anomalies"] or 0 for r in curve),
        "atomicity_violations": sum(
            (r["txn"] or {}).get("atomicity_violations", 0)
            for r in curve),
        "migration_blip_p99_ms": {
            str(r["shards"]): _migration_gate(r)[0]
            ["migration_blip_p99_ms"]
            for r in curve if _migration_gate(r)[0] is not None},
        "migration_gates_ok": doc["migration_gates_ok"],
    }))
    return worst


if __name__ == "__main__":
    raise SystemExit(main())
